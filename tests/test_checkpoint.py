"""Checkpoint/resume tests: stop after any super-step, resume later, on a
different engine/mesh — counts and discoveries must come out identical to an
uninterrupted run. Plus the crash-safety layer (ISSUE 8): atomic writes,
keep-K rotation, the embedded payload digest, the typed
``CheckpointCorrupt`` on torn files with automatic rotation fallback, and
the in-loop auto-checkpointer on both engines."""

import json
import os

import numpy as np
import pytest

import jax

from stateright_tpu import checkpoint as ck_mod
from stateright_tpu.models.two_phase_commit import PackedTwoPhaseSys
from stateright_tpu.parallel import default_mesh


_REF = None


def _full_run_reference():
    global _REF
    if _REF is None:
        _REF = PackedTwoPhaseSys(4).checker().spawn_xla(
            frontier_capacity=1 << 10, table_capacity=1 << 13
        ).join()
    return _REF


def test_single_chip_save_resume_roundtrip(tmp_path):
    ref = _full_run_reference()
    path = str(tmp_path / "ck.npz")

    partial = PackedTwoPhaseSys(4).checker().spawn_xla(
        frontier_capacity=1 << 10, table_capacity=1 << 13
    )
    for _ in range(4):  # part-way through the 14-level space
        partial._run_block()
    partial.save_checkpoint(path)

    resumed = PackedTwoPhaseSys(4).checker().spawn_xla(
        frontier_capacity=1 << 10, table_capacity=1 << 13, checkpoint=path
    )
    assert resumed.state_count() == partial.state_count()
    assert resumed.unique_state_count() == partial.unique_state_count()
    resumed.join()
    assert resumed.unique_state_count() == ref.unique_state_count() == 1_568
    assert resumed.state_count() == ref.state_count()
    assert resumed.max_depth() == ref.max_depth()
    assert set(resumed.discoveries()) == set(ref.discoveries())
    resumed.assert_properties()


def test_resume_with_different_capacities(tmp_path):
    path = str(tmp_path / "ck.npz")
    partial = PackedTwoPhaseSys(4).checker().spawn_xla(
        frontier_capacity=1 << 10, table_capacity=1 << 13
    )
    for _ in range(4):
        partial._run_block()
    partial.save_checkpoint(path)
    resumed = PackedTwoPhaseSys(4).checker().spawn_xla(
        frontier_capacity=1 << 5, table_capacity=1 << 6, checkpoint=path
    ).join()
    assert resumed.unique_state_count() == 1_568


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs the 8-device mesh")
def test_cross_engine_single_chip_to_sharded(tmp_path):
    path = str(tmp_path / "ck.npz")
    partial = PackedTwoPhaseSys(4).checker().spawn_xla(
        frontier_capacity=1 << 10, table_capacity=1 << 13
    )
    for _ in range(5):
        partial._run_block()
    partial.save_checkpoint(path)

    resumed = PackedTwoPhaseSys(4).checker().spawn_xla(
        mesh=default_mesh(8),
        frontier_capacity=1 << 10,
        table_capacity=1 << 13,
        checkpoint=path,
    )
    assert resumed.unique_state_count() == partial.unique_state_count()
    assert resumed.state_count() == partial.state_count()
    resumed.join()
    # The full-coverage pins (bench.py EXPECTED_2PC[4]): a cross-engine
    # resume reports the exact generated AND unique totals of an
    # uninterrupted run, and finds the same properties.
    assert resumed.unique_state_count() == 1_568
    assert resumed.state_count() == 8_258
    assert resumed.metrics()["resumed_from"] == path
    ref = _full_run_reference()
    assert resumed.max_depth() == ref.max_depth()
    assert set(resumed.discoveries()) == set(ref.discoveries())
    resumed.assert_properties()


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs the 8-device mesh")
def test_cross_engine_sharded_to_single_chip(tmp_path):
    path = str(tmp_path / "ck.npz")
    partial = PackedTwoPhaseSys(4).checker().spawn_xla(
        mesh=default_mesh(8), frontier_capacity=1 << 10, table_capacity=1 << 13
    )
    for _ in range(5):
        partial._run_block()
    partial.save_checkpoint(path)

    resumed = PackedTwoPhaseSys(4).checker().spawn_xla(
        frontier_capacity=1 << 10, table_capacity=1 << 13, checkpoint=path
    ).join()
    assert resumed.unique_state_count() == 1_568
    assert resumed.state_count() == 8_258
    ref = _full_run_reference()
    assert resumed.max_depth() == ref.max_depth()
    assert set(resumed.discoveries()) == set(ref.discoveries())
    resumed.assert_properties()


def test_checkpoint_rejects_wrong_model(tmp_path):
    path = str(tmp_path / "ck.npz")
    PackedTwoPhaseSys(4).checker().spawn_xla(
        frontier_capacity=1 << 10, table_capacity=1 << 13
    ).save_checkpoint(path)
    with pytest.raises(ValueError, match="does not match"):
        PackedTwoPhaseSys(5).checker().spawn_xla(
            frontier_capacity=1 << 10, table_capacity=1 << 13, checkpoint=path
        )


# --- crash-safety: atomic writes, rotation, digest, typed corruption ------


def _partial(n_blocks=4, **kw):
    c = PackedTwoPhaseSys(4).checker().spawn_xla(
        frontier_capacity=1 << 10, table_capacity=1 << 13, **kw
    )
    for _ in range(n_blocks):
        c._run_block()
    return c


def test_save_is_atomic_no_temp_left(tmp_path):
    path = str(tmp_path / "ck.npz")
    c = _partial(levels_per_dispatch=1)
    c.save_checkpoint(path)
    # The write went live via os.replace; no temp file survives success.
    assert sorted(os.listdir(tmp_path)) == ["ck.npz"]
    ck_mod.load_checkpoint(path)  # and the live file verifies clean


def test_rotation_keeps_last_k(tmp_path):
    path = str(tmp_path / "ck.npz")
    c = _partial(n_blocks=2, levels_per_dispatch=1)
    depths = []
    for _ in range(4):  # 4 saves at keep=3: the oldest falls off
        c.save_checkpoint(path, keep=3)
        depths.append(c._depth)
        c._run_block()
    rots = ck_mod.rotations(path)
    assert rots == [path, f"{path}.1", f"{path}.2"]
    # Newest first: the live file has the last save's depth, .1 the one
    # before, .2 the one before that; the first save was discarded.
    got = [ck_mod.load_checkpoint(p)["meta"]["depth"] for p in rots]
    assert got == depths[:0:-1]


def test_truncated_checkpoint_raises_typed(tmp_path):
    path = str(tmp_path / "ck.npz")
    _partial().save_checkpoint(path)
    with open(path, "r+b") as fh:
        fh.truncate(os.path.getsize(path) // 3)
    with pytest.raises(ck_mod.CheckpointCorrupt):
        ck_mod.load_checkpoint(path)
    # Not valid, and with no older rotation there is nothing to fall
    # back to.
    assert ck_mod.latest_valid_checkpoint(path) is None


def test_payload_digest_detects_tampering(tmp_path):
    # A well-formed archive whose payload no longer matches the embedded
    # digest (bit rot / a foreign writer): the self-verification catches
    # what zipfile-level checks cannot.
    path = str(tmp_path / "ck.npz")
    _partial().save_checkpoint(path)
    with np.load(path) as z:
        members = {k: np.asarray(z[k]) for k in z.files}
    assert members["key_lo"].size > 0
    members["key_lo"] = members["key_lo"] ^ np.uint32(1)
    np.savez_compressed(path, **members)  # meta (and its digest) unchanged
    with pytest.raises(ck_mod.CheckpointCorrupt, match="digest mismatch"):
        ck_mod.load_checkpoint(path)


def test_latest_valid_falls_back_past_torn_rotation(tmp_path):
    path = str(tmp_path / "ck.npz")
    c = _partial(levels_per_dispatch=1)
    c.save_checkpoint(path, keep=2)
    c._run_block()
    c.save_checkpoint(path, keep=2)
    with open(path, "r+b") as fh:
        fh.truncate(os.path.getsize(path) // 2)
    assert ck_mod.latest_valid_checkpoint(path) == f"{path}.1"
    resumed = PackedTwoPhaseSys(4).checker().spawn_xla(
        frontier_capacity=1 << 10, table_capacity=1 << 13,
        checkpoint=f"{path}.1",
    ).join()
    assert resumed.state_count() == 8_258
    assert resumed.unique_state_count() == 1_568


# --- in-loop auto-checkpointing -------------------------------------------


def test_autockpt_level_cadence_and_resume(tmp_path):
    path = str(tmp_path / "auto.npz")
    c = PackedTwoPhaseSys(4).checker().spawn_xla(
        frontier_capacity=1 << 10, table_capacity=1 << 13,
        levels_per_dispatch=1,
        checkpoint_to=path, checkpoint_every=2, checkpoint_keep=3,
    ).join()
    m = c.metrics()
    assert m["checkpoint_to"] == path
    assert m["checkpoints_written"] >= 3
    assert m["last_checkpoint_level"] is not None
    assert m["resumed_from"] is None
    assert len(ck_mod.rotations(path)) == 3  # keep bound respected
    # The engine-visible gauge matches the newest rotation's metadata.
    latest = ck_mod.latest_valid_checkpoint(path)
    meta = ck_mod.load_checkpoint(latest)["meta"]
    assert meta["depth"] == m["last_checkpoint_level"]
    # Resuming the newest auto-checkpoint converges to the exact totals.
    resumed = PackedTwoPhaseSys(4).checker().spawn_xla(
        frontier_capacity=1 << 10, table_capacity=1 << 13, checkpoint=latest
    ).join()
    assert resumed.state_count() == c.state_count() == 8_258
    assert resumed.unique_state_count() == 1_568
    assert resumed.metrics()["resumed_from"] == latest


def test_autockpt_seconds_cadence(tmp_path):
    path = str(tmp_path / "auto_s.npz")
    c = PackedTwoPhaseSys(4).checker().spawn_xla(
        frontier_capacity=1 << 10, table_capacity=1 << 13,
        levels_per_dispatch=1,
        checkpoint_to=path, checkpoint_every="0.001s",
    ).join()
    # Sub-millisecond cadence => a write at (nearly) every dispatch
    # boundary; at minimum the cadence fired repeatedly.
    assert c.metrics()["checkpoints_written"] >= 3


def test_autockpt_env_knobs(tmp_path, monkeypatch):
    path = str(tmp_path / "env.npz")
    monkeypatch.setenv("STPU_CHECKPOINT_TO", path)
    monkeypatch.setenv("STPU_CHECKPOINT_EVERY", "1")
    monkeypatch.setenv("STPU_CHECKPOINT_KEEP", "2")
    c = PackedTwoPhaseSys(4).checker().spawn_xla(
        frontier_capacity=1 << 10, table_capacity=1 << 13,
        levels_per_dispatch=1,
    ).join()
    assert c.metrics()["checkpoints_written"] >= 2
    assert len(ck_mod.rotations(path)) == 2
    ck_mod.load_checkpoint(path)


def test_autockpt_bad_cadence_rejected(tmp_path):
    with pytest.raises(ValueError, match="checkpoint_every"):
        PackedTwoPhaseSys(4).checker().spawn_xla(
            frontier_capacity=1 << 10, table_capacity=1 << 13,
            checkpoint_to=str(tmp_path / "x.npz"), checkpoint_every="soon",
        )


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs the 8-device mesh")
def test_autockpt_sharded_then_single_chip_resume(tmp_path):
    path = str(tmp_path / "mesh_auto.npz")
    c = PackedTwoPhaseSys(4).checker().spawn_xla(
        mesh=default_mesh(8),
        frontier_capacity=1 << 10, table_capacity=1 << 13,
        levels_per_dispatch=1,
        checkpoint_to=path, checkpoint_every=1,
    ).join()
    m = c.metrics()
    assert m["checkpoints_written"] >= 3
    assert m["last_checkpoint_level"] is not None
    latest = ck_mod.latest_valid_checkpoint(path)
    assert latest is not None
    # A mesh-written auto-checkpoint resumes on the single-chip engine —
    # the cross-engine contract holds for the recovery path too.
    resumed = PackedTwoPhaseSys(4).checker().spawn_xla(
        frontier_capacity=1 << 10, table_capacity=1 << 13, checkpoint=latest
    ).join()
    assert resumed.state_count() == 8_258
    assert resumed.unique_state_count() == 1_568


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs the 8-device mesh")
def test_autockpt_single_chip_then_sharded_resume(tmp_path):
    """The REVERSE auto-checkpoint direction ROADMAP item 2 left open
    (sharded-auto -> single resume is pinned above): a single-chip run's
    in-loop auto-checkpoint rotations resume on the SHARDED mesh engine
    — which then keeps auto-checkpointing rotations of its own — with
    exact full-coverage counts and identical discoveries."""
    path = str(tmp_path / "chip_auto.npz")
    partial = PackedTwoPhaseSys(4).checker().spawn_xla(
        frontier_capacity=1 << 10, table_capacity=1 << 13,
        levels_per_dispatch=1,
        checkpoint_to=path, checkpoint_every=1, checkpoint_keep=3,
    )
    for _ in range(5):  # part-way through the 14-level space
        partial._run_block()
    assert partial.metrics()["checkpoints_written"] >= 3
    latest = ck_mod.latest_valid_checkpoint(path)
    assert latest is not None

    mesh_path = str(tmp_path / "mesh_auto.npz")
    resumed = PackedTwoPhaseSys(4).checker().spawn_xla(
        mesh=default_mesh(8),
        frontier_capacity=1 << 10, table_capacity=1 << 13,
        levels_per_dispatch=1,
        checkpoint=latest,
        checkpoint_to=mesh_path, checkpoint_every=1,
    )
    assert resumed.state_count() == partial.state_count()
    assert resumed.unique_state_count() == partial.unique_state_count()
    resumed.join()
    assert resumed.state_count() == 8_258
    assert resumed.unique_state_count() == 1_568
    assert resumed.metrics()["resumed_from"] == latest
    ref = _full_run_reference()
    assert resumed.max_depth() == ref.max_depth()
    assert set(resumed.discoveries()) == set(ref.discoveries())
    resumed.assert_properties()
    # The mesh leg auto-checkpointed rotations of its own, and the
    # newest one round-trips BACK onto the single-chip engine — the
    # full chip -> mesh -> chip recovery cycle is closed.
    assert resumed.metrics()["checkpoints_written"] >= 1
    mesh_latest = ck_mod.latest_valid_checkpoint(mesh_path)
    assert mesh_latest is not None
    back = PackedTwoPhaseSys(4).checker().spawn_xla(
        frontier_capacity=1 << 10, table_capacity=1 << 13,
        checkpoint=mesh_latest,
    ).join()
    assert back.state_count() == 8_258
    assert back.unique_state_count() == 1_568


def test_checkpoint_preserves_discovery_pins(tmp_path):
    # Run to completion (both sometimes-properties found), checkpoint, and
    # resume: the resumed checker must report the same witnesses without
    # re-searching.
    path = str(tmp_path / "ck.npz")
    done = PackedTwoPhaseSys(3).checker().spawn_xla(
        frontier_capacity=1 << 10, table_capacity=1 << 13
    ).join()
    done.save_checkpoint(path)
    resumed = PackedTwoPhaseSys(3).checker().spawn_xla(
        frontier_capacity=1 << 10, table_capacity=1 << 13, checkpoint=path
    )
    assert resumed._found_names == done._found_names
    a = {n: p.into_actions() for n, p in done.discoveries().items()}
    b = {n: p.into_actions() for n, p in resumed.discoveries().items()}
    assert a == b
