"""Packed Paxos on the device engine — the flagship actor example on
``spawn_xla`` (VERDICT.md round-1 item #3).

Oracle: the reference's own test asserts 16,668 unique states at 2 clients /
3 servers on an unordered non-duplicating network and an 8-action shortest
witness for "value chosen" (examples/paxos.rs:294-346). The packed model must
agree with the object model action-for-action (the differential test) and
end-to-end on the device engine (the full-coverage test).
"""

import random

import numpy as np
import pytest

from stateright_tpu.actor.network import Envelope
from stateright_tpu.models.paxos import PackedPaxos, paxos_model


def _sample_states(model, n, seed=7, walk=4000):
    """Random-walk sample of reachable states (mixed depths)."""
    rng = random.Random(seed)
    init = model.init_states()[0]
    sample = {init}
    cur = init
    for _ in range(walk):
        steps = list(model.next_steps(cur))
        if not steps:
            cur = init
            continue
        _, cur = rng.choice(steps)
        sample.add(cur)
        if len(sample) >= n:
            break
    return sorted(sample, key=repr)


def test_codec_round_trips_and_differential_step_parity():
    """For every sampled reachable state: pack/unpack is exact, and the
    device action grid agrees with the object model action-for-action —
    same enabled (non-no-op) deliveries, identical successor words."""
    import jax
    import jax.numpy as jnp

    m = PackedPaxos(2, 3)
    states = _sample_states(m._inner, 150)
    packed = np.stack([m.pack(s) for s in states])
    for s, row in zip(states, packed):
        assert m.unpack(row) == s, f"codec round-trip mismatch for {s!r}"

    nxt, valid, ovf = jax.jit(jax.vmap(m.packed_step))(jnp.asarray(packed))
    nxt, valid, ovf = np.asarray(nxt), np.asarray(valid), np.asarray(ovf)
    assert not ovf.any(), "codec overflow on reachable states"

    for si, s in enumerate(states):
        obj = {}
        for action, ns in m._inner.next_steps(s):
            code = m._env_code[Envelope(action.src, action.dst, action.msg)]
            obj[code] = ns
        assert set(np.nonzero(valid[si])[0].tolist()) == set(obj), (
            f"enabled-action mismatch at state {si}"
        )
        for code, ns in obj.items():
            np.testing.assert_array_equal(
                nxt[si, code],
                m.pack(ns),
                err_msg=f"successor mismatch: state {si}, envelope {m._envs[code]!r}",
            )


@pytest.mark.slow
def test_xla_matches_the_16668_state_oracle():
    """Full coverage on the device engine: the reference's exact unique-state
    count, a clean linearizability verdict (host-verified candidates all
    pass), and the 8-action shortest witness for "value chosen"."""
    from stateright_tpu.actor import register as reg

    m = PackedPaxos(2, 3)
    xc = m.checker().spawn_xla(
        frontier_capacity=1 << 12,
        table_capacity=1 << 16,
        host_verified_cap=4096,
    ).join()
    assert xc.unique_state_count() == 16_668  # examples/paxos.rs:321,345
    xc.assert_properties()
    witness = xc.discoveries()["value chosen"]
    pairs = witness.into_vec()
    actions = [a for _s, a in pairs if a is not None]
    assert len(actions) == 8  # BFS shortest witness (examples/paxos.rs:311-320)
    assert isinstance(actions[0].msg, reg.Put)
    assert isinstance(actions[-1].msg, reg.Get)
    final = pairs[-1][0]
    assert any(
        isinstance(env.msg, reg.GetOk) and env.msg.value is not None
        for env in final.network.iter_deliverable()
    )


@pytest.mark.slow
def test_three_client_codec_step_and_bounded_parity():
    """Paxos at 3 clients / 3 servers (the BASELINE.json ``paxos check 3``
    config): codec round-trips, device step parity on a reachable sample,
    and exact bounded-depth count parity against the host oracle (depth 8:
    3,279 generated / 1,969 unique). The full 3-client space is far past
    oracle range; full-coverage runs are device-engine territory."""
    import jax
    import jax.numpy as jnp

    m = PackedPaxos(3, 3)
    states = _sample_states(m._inner, 100)
    packed = np.stack([m.pack(s) for s in states])
    for s, row in zip(states, packed):
        assert m.unpack(row) == s
    nxt, valid, ovf = jax.jit(jax.vmap(m.packed_step))(jnp.asarray(packed))
    nxt, valid, ovf = np.asarray(nxt), np.asarray(valid), np.asarray(ovf)
    assert not ovf.any()
    for si, s in enumerate(states):
        want = {m.pack(ns).tobytes() for _, ns in m._inner.next_steps(s)}
        got = {nxt[si, a].tobytes() for a in range(m.max_actions) if valid[si, a]}
        assert got == want, f"step mismatch at state {si}"

    h = paxos_model(3, 3).checker().target_max_depth(8).spawn_bfs().join()
    c = (
        PackedPaxos(3, 3)
        .checker()
        .target_max_depth(8)
        .spawn_xla(frontier_capacity=1 << 13, table_capacity=1 << 17)
        .join()
    )
    assert (c.state_count(), c.unique_state_count()) == (
        h.state_count(),
        h.unique_state_count(),
    ) == (3279, 1969)


def test_sorted_dedup_matches_hash_at_wide_state_words():
    """The planes superstep at Paxos width (W=25 state words, hv
    linearizability candidates in flight): counts and the discovery set
    must match the hash/rows engine exactly. Depth-bounded to keep the
    CPU run short; full coverage is the test above."""
    kw = dict(
        frontier_capacity=1 << 11, table_capacity=1 << 14, host_verified_cap=4096
    )
    a = (
        PackedPaxos(2, 3).checker().target_max_depth(9)
        .spawn_xla(dedup="hash", **kw).join()
    )
    b = (
        PackedPaxos(2, 3).checker().target_max_depth(9)
        .spawn_xla(dedup="sorted", **kw).join()
    )
    assert (a.state_count(), a.unique_state_count(), a.max_depth()) == (
        b.state_count(),
        b.unique_state_count(),
        b.max_depth(),
    )
    assert set(a.discoveries()) == set(b.discoveries())
