"""Packed ordered-network ABD: FifoLanes over the quorum protocol.

The reference harness's ``linearizable-register check 2 ordered`` config
(bench.sh:33, BASELINE.json). The reference has no exact-count oracle for
ordered ABD, so parity is engine-vs-engine: the packed FifoLanes model must
agree action-for-action and in full coverage with this package's object
``OrderedNetwork`` model (which passes the reference's ordered-semantics
regression matrix, model.rs:795-964).
"""

import random

import numpy as np
import pytest

from stateright_tpu.actor import Network
from stateright_tpu.models.linearizable_register import (
    PackedAbdOrdered,
    linearizable_register_model,
)


def _sample_states(inner, n, seed=5):
    rng = random.Random(seed)
    init = inner.init_states()[0]
    sample = {init}
    cur = init
    for _ in range(6000):
        steps = list(inner.next_steps(cur))
        if not steps:
            cur = init
            continue
        _, cur = rng.choice(steps)
        sample.add(cur)
        if len(sample) >= n:
            break
    return sorted(sample, key=repr)


def test_codec_round_trips_and_step_parity():
    import jax
    import jax.numpy as jnp

    m = PackedAbdOrdered(2, 2)
    states = _sample_states(m._inner, 150)
    packed = np.stack([m.pack(s) for s in states])
    for s, row in zip(states, packed):
        assert m.unpack(row) == s
    nxt, valid, ovf = jax.jit(jax.vmap(m.packed_step))(jnp.asarray(packed))
    nxt, valid, ovf = np.asarray(nxt), np.asarray(valid), np.asarray(ovf)
    assert not ovf.any()
    for si, s in enumerate(states):
        want = {m.pack(ns).tobytes() for _, ns in m._inner.next_steps(s)}
        got = {
            nxt[si, a].tobytes() for a in range(m.max_actions) if valid[si, a]
        }
        assert got == want, f"step mismatch at state {si}"


def test_full_coverage_matches_host_engine():
    h = (
        linearizable_register_model(2, 2, Network.new_ordered())
        .checker()
        .spawn_bfs()
        .join()
    )
    c = (
        PackedAbdOrdered(2, 2)
        .checker()
        .spawn_xla(frontier_capacity=1 << 10, table_capacity=1 << 12)
        .join()
    )
    assert (c.state_count(), c.unique_state_count(), c.max_depth()) == (
        h.state_count(),
        h.unique_state_count(),
        h.max_depth(),
    ) == (813, 564, 25)
    c.assert_properties()
    assert len(c.discoveries()["value chosen"]) == len(
        h.discoveries()["value chosen"]
    )


@pytest.mark.slow
def test_three_client_full_coverage_parity():
    # 3 clients over ordered channels with device-exact 3-thread
    # linearizability: 63,053 generated / 36,213 unique (engine-vs-engine;
    # pinned from the host oracle run).
    c = (
        PackedAbdOrdered(3, 2)
        .checker()
        .spawn_xla(frontier_capacity=1 << 11, table_capacity=1 << 14)
        .join()
    )
    c.assert_properties()
    assert (c.state_count(), c.unique_state_count()) == (63053, 36213)


def test_invalid_sizes_raise():
    with pytest.raises(ValueError):
        PackedAbdOrdered(2, 3)
    with pytest.raises(ValueError):
        PackedAbdOrdered(4, 2)
