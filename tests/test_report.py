"""Reporter format parity, ported from /root/reference/src/checker.rs:669-758."""

import io

from stateright_tpu import WriteReporter
from stateright_tpu.test_util import LinearEquation


def test_report_includes_property_names_and_paths_bfs():
    written = io.StringIO()
    LinearEquation(2, 10, 14).checker().spawn_bfs().report(WriteReporter(written))
    output = written.getvalue()
    assert output.startswith(
        "Checking. states=1, unique=1, depth=0\n"
        "Done. states=15, unique=12, depth=4, sec="
    ), output
    assert output.endswith(
        'Discovered "solvable" example Path[3]:\n'
        "- IncreaseX\n"
        "- IncreaseX\n"
        "- IncreaseY\n"
    ), output


def test_report_includes_property_names_and_paths_dfs():
    written = io.StringIO()
    LinearEquation(2, 10, 14).checker().spawn_dfs().report(WriteReporter(written))
    output = written.getvalue()
    assert output.startswith(
        "Checking. states=1, unique=1, depth=0\n"
        "Done. states=55, unique=55, depth=28, sec="
    ), output
    assert output.endswith(
        'Discovered "solvable" example Path[27]:\n' + "- IncreaseY\n" * 27
    ), output
