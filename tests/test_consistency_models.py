"""Consistency-model variants of the single-copy register on the device
engine: sequential consistency end-to-end, N-client device-exact
linearizability, and the host-verified fallback past the interleaving
budget.

The reference defines ``SequentialConsistencyTester``
(sequential_consistency.rs:53-241) but wires no example to it; here the
single-copy register runs under either tester, on both engines, with parity
between them. ``device_exact=False`` (the default past
``semantics.device.MAX_PATTERNS_EXACT``, i.e. 5+ clients) exercises the
engine's ``host_verified_properties`` path with a diverse-subsample
conservative predicate — its first real (non-synthetic) customer.
"""

import pytest

from stateright_tpu.models.single_copy_register import (
    PackedSingleCopyRegister,
    single_copy_register_model,
)


def test_sc_one_server_full_coverage_parity():
    # One copy is linearizable, hence sequentially consistent: full
    # coverage, and the SC history (no prereq snapshots) collapses states
    # exactly like the host tester's equality (57 < the lin config's 93).
    c = (
        PackedSingleCopyRegister(2, 1, consistency="sequential")
        .checker()
        .spawn_xla(frontier_capacity=1 << 10, table_capacity=1 << 12)
        .join()
    )
    c.assert_properties()
    h = (
        single_copy_register_model(2, 1, consistency="sequential")
        .checker()
        .spawn_bfs()
        .join()
    )
    assert (c.state_count(), c.unique_state_count()) == (
        h.state_count(),
        h.unique_state_count(),
    )
    assert c.unique_state_count() == 57


def test_sc_two_servers_counterexample_parity():
    # Two copies violate SC as well (a client can read back None after its
    # own completed write — no serial order allows it): both engines find a
    # depth-minimal witness whose final history the host serializer rejects.
    c = (
        PackedSingleCopyRegister(2, 2, consistency="sequential")
        .checker()
        .spawn_xla(frontier_capacity=1 << 10, table_capacity=1 << 12)
        .join()
    )
    h = (
        single_copy_register_model(2, 2, consistency="sequential")
        .checker()
        .spawn_bfs()
        .join()
    )
    pc = c.discoveries()["sequentially consistent"]
    ph = h.discoveries()["sequentially consistent"]
    assert len(pc) == len(ph)
    assert pc.last_state().history.serialized_history() is None


def test_three_client_device_exact_full_coverage():
    # T=3 linearizability fully on device (1,680 interleavings/state):
    # exact count parity with the host oracle (BASELINE.md: 6,778/4,243).
    m = PackedSingleCopyRegister(3, 1)
    assert not getattr(m, "host_verified_properties", None)
    c = m.checker().spawn_xla(
        frontier_capacity=1 << 11, table_capacity=1 << 14
    ).join()
    c.assert_properties()
    assert (c.state_count(), c.unique_state_count()) == (6778, 4243)


@pytest.mark.slow
def test_four_client_host_verified_bounded_parity():
    # 4 threads = 369,600 interleavings. Since round 4 the default is
    # device-EXACT (chunked scan); device_exact=False pins the engine's
    # host_verified_properties machinery — the sampled one-sided device
    # predicate with host-serializer confirmation of flagged rows (the
    # production path for 5+ clients). Bounded-depth counts must still
    # match the oracle exactly.
    m = PackedSingleCopyRegister(4, 1, device_exact=False)
    assert m.host_verified_properties == frozenset({"linearizable"})
    c = (
        m.checker()
        .target_max_depth(6)
        .spawn_xla(
            frontier_capacity=1 << 12,
            table_capacity=1 << 15,
            host_verified_cap=4096,
        )
        .join()
    )
    h = (
        single_copy_register_model(4, 1)
        .checker()
        .target_max_depth(6)
        .spawn_bfs()
        .join()
    )
    assert (c.state_count(), c.unique_state_count()) == (
        h.state_count(),
        h.unique_state_count(),
    )
    assert "linearizable" not in c.discoveries()


@pytest.mark.slow
def test_four_client_host_verified_finds_real_counterexample():
    # 4c/2s reaches genuinely non-linearizable states: the hv path must
    # confirm one through the host serializer at the oracle's witness depth.
    c = (
        PackedSingleCopyRegister(4, 2, device_exact=False)
        .checker()
        .spawn_xla(
            frontier_capacity=1 << 12,
            table_capacity=1 << 15,
            host_verified_cap=4096,
        )
        .join()
    )
    h = single_copy_register_model(4, 2).checker().spawn_bfs().join()
    pc = c.discoveries()["linearizable"]
    assert len(pc) == len(h.discoveries()["linearizable"])
    assert pc.last_state().history.serialized_history() is None


@pytest.mark.slow
def test_four_client_device_exact_bounded_parity():
    # The round-4 widened regime: 4 clients checked device-EXACT (369,600
    # interleavings, chunked under lax.scan) with no host fallback —
    # bounded-depth counts match the oracle and nothing is flagged.
    m = PackedSingleCopyRegister(4, 1)
    assert not getattr(m, "host_verified_properties", None)
    c = (
        m.checker()
        .target_max_depth(6)
        .spawn_xla(frontier_capacity=1 << 12, table_capacity=1 << 15)
        .join()
    )
    h = (
        single_copy_register_model(4, 1)
        .checker()
        .target_max_depth(6)
        .spawn_bfs()
        .join()
    )
    assert (c.state_count(), c.unique_state_count()) == (
        h.state_count(),
        h.unique_state_count(),
    )
    assert "linearizable" not in c.discoveries()
