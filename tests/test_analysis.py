"""stpu-lint (stateright_tpu/analysis): every rule ID trips on a
deliberately-bad golden kernel (positive detection), the shipped tree
sweeps clean under the justified waivers, and the waiver file
round-trips.

The golden fixtures are the pinned pathologies rebuilt in miniature —
each one is the exact shape a backend broke on (docs/static-analysis.md
carries the history), so a rule that stops firing here has stopped
guarding the real thing.
"""

import os
import textwrap

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from stateright_tpu.analysis import (
    Finding,
    WaiverError,
    apply_waivers,
    load_waivers,
    run_lint,
)
from stateright_tpu.analysis.astlint import lint_file, run_ast_pass
from stateright_tpu.analysis.jaxpr_lint import (
    cond_flush_sorts,
    mosaic_kernel_rules,
    output_transposes,
    taint_scatters,
    wide_sorts,
)
from stateright_tpu.analysis.surfaces import run_sweep


def _sds(shape, dtype):
    return jax.ShapeDtypeStruct(shape, dtype)


# --- STPU001: data-dependent scatter in a vmapped kernel --------------------


def test_stpu001_flags_traced_index_scatter(monkeypatch):
    import stateright_tpu.packing as packing

    monkeypatch.setattr(packing, "ONE_HOT_WRITES", True)

    def bad(words, i):  # the round-3/5 paxos-drift shape
        return words.at[i].set(jnp.uint32(1))

    jx = jax.make_jaxpr(jax.vmap(bad))(
        _sds((4096, 8), jnp.uint32), _sds((4096,), jnp.uint32)
    )
    hits = taint_scatters(jx, "golden:stpu001")
    assert [f.rule for f in hits] == ["STPU001"]
    assert "stateright_tpu" not in hits[0].file  # anchored to THIS file
    assert hits[0].line > 0


def test_stpu001_static_index_scatter_is_exempt():
    def ok(words):  # static-index write: XLA folds it, drift never repro'd
        return words.at[3].set(jnp.uint32(1))

    jx = jax.make_jaxpr(jax.vmap(ok))(_sds((4096, 8), jnp.uint32))
    assert taint_scatters(jx, "golden:static") == []


def test_stpu001_word_update_path_is_clean(monkeypatch):
    """The sanctioned lowering (packing._word_update under the
    accelerator pin) emits no scatter at all — the generalized form of
    the old test_packing HLO pin."""
    import stateright_tpu.packing as packing
    from stateright_tpu.packing import LayoutBuilder

    monkeypatch.setattr(packing, "ONE_HOT_WRITES", True)
    lay = LayoutBuilder().array("xs", 6, 4).finish()

    def good(words, i):
        return lay.set(words, "xs", 3, i)

    jx = jax.make_jaxpr(jax.vmap(good))(
        _sds((4096, lay.words), jnp.uint32), _sds((4096,), jnp.uint32)
    )
    assert taint_scatters(jx, "golden:word-update") == []


# --- STPU002: transpose fused into a vmapped kernel -------------------------


def test_stpu002_flags_out_axes_transpose():
    def kernel(words):
        return words * jnp.uint32(2)

    jx = jax.make_jaxpr(jax.vmap(kernel, out_axes=1))(_sds((64, 4), jnp.uint32))
    hits = output_transposes(jx, "golden:stpu002")
    assert [f.rule for f in hits] == ["STPU002"]
    assert "transpose" in hits[0].excerpt
    assert "out_axes != 0" in hits[0].message  # the direct-output form

    clean = jax.make_jaxpr(jax.vmap(kernel))(_sds((64, 4), jnp.uint32))
    assert output_transposes(clean, "golden:rows") == []


def test_stpu002_flags_mid_kernel_transpose():
    """The documented gap, closed: a transpose buried BETWEEN ops (here
    a nested vmap(out_axes=1) whose transpose feeds a further add, so it
    does not produce the surface's outputs directly) is still the
    transpose-fused-into-vmap shape XLA:CPU miscompiles."""

    def inner(col):
        return col + jnp.uint32(1)

    def kernel(words):  # words [4, 4]
        cols = jax.vmap(inner, out_axes=1)(words)  # transpose, mid-kernel
        return cols + jnp.uint32(1)  # ...consumed by a further op

    jx = jax.make_jaxpr(jax.vmap(kernel))(_sds((64, 4, 4), jnp.uint32))
    hits = output_transposes(jx, "golden:mid-kernel")
    assert hits and all(f.rule == "STPU002" for f in hits)
    assert any("mid-kernel" in f.message for f in hits)


# --- STPU003: the wide-W sort compile-stall shape ---------------------------


def test_stpu003_flags_wide_sort():
    W = 25  # paxos width: the round-5 stall was its W+3-operand sort

    def bad(*lanes):
        return jax.lax.sort(lanes, num_keys=1)

    args = [_sds((1024,), jnp.uint32) for _ in range(W + 3)]
    jx = jax.make_jaxpr(bad)(*args)
    hits = wide_sorts(jx, "golden:stpu003")
    assert [f.rule for f in hits] == ["STPU003"]
    assert "28-operand" in hits[0].message

    ok = jax.make_jaxpr(bad)(*args[:12])  # the chip-proven W<=8 class
    assert wide_sorts(ok, "golden:narrow") == []


# --- STPU004: deltaset flush under lax.cond ---------------------------------


def test_stpu004_flags_flush_under_cond():
    from stateright_tpu.ops import deltaset

    ds = deltaset.make(1 << 13, jnp)

    def bad(ds, pred):  # the round-5 "TPU worker crashed" shape
        return jax.lax.cond(
            pred, lambda d: deltaset.maintain(d)[0], lambda d: d, ds
        )

    jx = jax.make_jaxpr(bad)(ds, _sds((), jnp.bool_))
    hits = cond_flush_sorts(jx, "golden:stpu004", ds.main_capacity)
    assert hits and all(f.rule == "STPU004" for f in hits)
    assert "ops/deltaset.py" in hits[0].file

    # The host-invoked form (the shipped protocol) is clean.
    ok = jax.make_jaxpr(deltaset.maintain)(ds)
    assert cond_flush_sorts(ok, "golden:maintain", ds.main_capacity) == []


# --- STPU005: Mosaic TC kernel rules ----------------------------------------


def _pallas_jaxpr(kernel, n=256):
    from jax.experimental import pallas as pl

    def run(x):
        return pl.pallas_call(
            kernel, out_shape=jax.ShapeDtypeStruct((n,), jnp.int32)
        )(x)

    return jax.make_jaxpr(run)(_sds((n,), jnp.int32))


def test_stpu005_flags_cumsum_in_kernel():
    def bad_kernel(x_ref, o_ref):  # the r5e first-silicon lowering gap
        o_ref[...] = jnp.cumsum(x_ref[...])

    hits = mosaic_kernel_rules(_pallas_jaxpr(bad_kernel), "golden:cumsum")
    assert hits and all(f.rule == "STPU005" for f in hits)
    assert "cumsum" in hits[0].message


def test_stpu005_flags_u32_f32_cast_in_kernel():
    def bad_kernel(x_ref, o_ref):
        f = x_ref[...].astype(jnp.uint32).astype(jnp.float32)  # direct cast
        o_ref[...] = f.astype(jnp.int32)

    hits = mosaic_kernel_rules(_pallas_jaxpr(bad_kernel), "golden:cast")
    assert any("u32<->f32" in f.message for f in hits)


def test_stpu005_i32_hop_is_clean():
    def ok_kernel(x_ref, o_ref):  # the sanctioned value-exact hop
        f = x_ref[...].astype(jnp.float32)
        o_ref[...] = f.astype(jnp.int32)

    assert mosaic_kernel_rules(_pallas_jaxpr(ok_kernel), "golden:hop") == []


def test_stpu005_shipped_kernels_preflight_for_tpu():
    """Registry #6 as one command: both ops/ pallas kernels lower for
    the TPU target from this CPU-only process (this is the check that
    caught the integer-reduction Mosaic gap in both kernels)."""
    reports = {r.name: r for r in run_sweep(only=["pallas:"])}
    assert {"pallas:compact", "pallas:merge"} <= set(reports)
    for rep in reports.values():
        assert rep.error == "", rep.error
        assert rep.findings == [], [f.message for f in rep.findings]


# --- STPU006: static VMEM budget for pallas kernels -------------------------


def test_stpu006_flags_oversized_vmem_kernel():
    """A kernel whose scratch ring alone blows the ~16 MiB v5e budget —
    today this shape is a runtime Mosaic allocation error discovered ON
    CHIP; the flight-check prices it statically."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from stateright_tpu.analysis.jaxpr_lint import vmem_budget

    def kernel(x_ref, o_ref, big_scratch):
        o_ref[...] = x_ref[...]

    def run(x):
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((256,), jnp.float32),
            scratch_shapes=[pltpu.VMEM((4096, 4096), jnp.float32)],  # 64 MiB
        )(x)

    jx = jax.make_jaxpr(run)(_sds((256,), jnp.float32))
    hits = vmem_budget(jx, "golden:stpu006")
    assert [f.rule for f in hits] == ["STPU006"]
    assert "VMEM footprint" in hits[0].message
    assert "scratch" in hits[0].message


def test_stpu006_shipped_kernels_fit_across_block_range():
    """Both shipped kernels price under the budget at every supported
    STPU_PALLAS_BLOCK (the per-block surfaces in the sweep)."""
    reports = {r.name: r for r in run_sweep(only=["pallas:vmem:"])}
    assert reports, "per-block vmem surfaces missing from the sweep"
    for rep in reports.values():
        assert rep.error == "", rep.error
        assert rep.findings == [], [f.message for f in rep.findings]


# --- STPU007: the compile-plan census ----------------------------------------


def test_stpu007_flags_over_budget_plan():
    from stateright_tpu.analysis.census import census_findings, plan_for

    plan = plan_for("2pc:3", "tpu", frontier_capacity=1 << 22)
    census = {"specs": {"2pc:3": {"tpu": plan}}}
    hits = census_findings(census)
    assert [f.rule for f in hits] == ["STPU007"]
    assert f"{plan['distinct_programs']} distinct" in hits[0].message
    assert plan["distinct_programs"] > plan["budget"]


def test_census_matches_shipped_and_planner():
    """The census is the SHIPPED registry run through the shared ladder
    planner — drift in either direction is a failure — and the warm set
    tools/warm_cache.py derives equals it exactly."""
    import importlib.util

    from stateright_tpu.analysis.census import build_census, census_findings, warm_specs
    from stateright_tpu.service.registry import SHIPPED, resolve
    from stateright_tpu.xla import default_cand_cap, ladder_buckets

    census = build_census()
    assert list(census["specs"]) == list(SHIPPED)
    assert census_findings(census) == []  # every shipped plan in budget
    assert warm_specs(census) == list(SHIPPED)

    # The census's shapes are the shared planner's, at the registry
    # capacities (spot-check one spec end to end).
    model, caps = resolve("paxos:2,3")
    plan = census["specs"]["paxos:2,3"]["tpu"]
    buckets = ladder_buckets(caps["frontier_capacity"])
    assert [s["bucket"] for s in plan["shapes"]] == buckets
    assert plan["shapes"][-1]["cand_cap"] == default_cand_cap(
        buckets[-1], model.max_actions, "tpu", env={}
    )

    # tools/warm_cache.py's default --specs goes through the same
    # derivation (the warm set is derived, not hand-maintained).
    spec = importlib.util.spec_from_file_location(
        "warm_cache", os.path.join(os.path.dirname(__file__), "..", "tools", "warm_cache.py")
    )
    wc = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(wc)
    assert wc.default_specs() == list(SHIPPED)


# --- STPU008: cross-backend lowering diff ------------------------------------


def test_stpu008_flags_one_sided_pathology_op():
    from stateright_tpu.analysis.jaxpr_lint import diff_lowering_inventories

    base = {"stablehlo.add", "stablehlo.compare", "stablehlo.iota"}
    hits = diff_lowering_inventories(
        "golden:stpu008",
        base | {"stablehlo.scatter"},  # cpu lowers a scatter...
        base,  # ...tpu lowers none — the dropped-write class
    )
    assert [f.rule for f in hits] == ["STPU008"]
    assert "stablehlo.scatter" in hits[0].message
    assert "cpu" in hits[0].excerpt

    # Symmetric inventories — even with pathology ops on BOTH sides —
    # are clean: the rule is about divergence, not presence (STPU001/003
    # own presence).
    both = base | {"stablehlo.sort"}
    assert diff_lowering_inventories("golden:same", both, both) == []
    # A non-registry op on one side only is noise, not a finding.
    assert (
        diff_lowering_inventories("golden:benign", base | {"stablehlo.tanh"}, base)
        == []
    )


def test_stpu008_shipped_kernels_lower_identically():
    """Both width classes' transition kernels produce identical
    pathology-op inventories on cpu and tpu lowerings (the integration
    form; the sweep runs these surfaces by default — the solo kernel,
    the ISSUE 16 batched mux superstep, and the ISSUE 19 symmetry
    canonicalization kernel)."""
    reports = {r.name: r for r in run_sweep(only=["lower:2pc:3"])}
    assert set(reports) == {
        "lower:2pc:3:packed_step",
        "lower:2pc:3:mux-superstep:k2",
        "lower:2pc:3:sym-canon",
    }
    for rep in reports.values():
        assert rep.error == "", rep.error
        assert rep.findings == [], [f.message for f in rep.findings]


# --- the sharded mesh engine is a traced surface -----------------------------


def test_sharded_superstep_is_a_registered_surface():
    """The second documented missing surface, closed: the mesh engine's
    shard_map superstep traces under the 8-device virtual CPU mesh (the
    config tests/conftest.py forces) in both dedup configs."""
    import jax as _jax

    reports = {r.name: r for r in run_sweep(only=["sharded-superstep"])}
    assert set(reports) == {
        "engine:2pc:3:sharded-superstep:hash",
        "engine:2pc:3:sharded-superstep:sorted",
    }
    for rep in reports.values():
        if len(_jax.devices()) < 8:  # pragma: no cover - conftest forces 8
            assert rep.skipped
            continue
        assert rep.error == "", rep.error
        assert rep.skipped == ""
        assert rep.findings == [], [f.message for f in rep.findings]


# --- AST rules (STPU101-103) ------------------------------------------------


def _lint_source(tmp_path, rel, text):
    p = tmp_path / rel
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(text))
    return lint_file(str(p), rel)


def test_stpu101_flags_at_write_in_models(tmp_path):
    hits = _lint_source(
        tmp_path,
        "models/bad_model.py",
        """
        def packed_step(self, words, i):
            return words.at[i].set(1)
        """,
    )
    assert [f.rule for f in hits] == ["STPU101"]
    assert ".at[i].set(1)" in hits[0].excerpt
    # The same write outside models/ is not this rule's business.
    assert (
        _lint_source(tmp_path, "ops/fine.py", "def f(w, i):\n    return w.at[i].set(1)\n")
        == []
    )


def test_stpu102_flags_bare_backend_bringup(tmp_path):
    hits = _lint_source(
        tmp_path, "cli_helper.py", "import jax\nds = jax.devices()\n"
    )
    assert [f.rule for f in hits] == ["STPU102"]
    # backend.py owns the guarded paths.
    assert (
        _lint_source(tmp_path, "backend.py", "import jax\nds = jax.devices()\n")
        == []
    )


def test_stpu103_flags_raw_heartbeat_write(tmp_path):
    hits = _lint_source(
        tmp_path,
        "service/sloppy.py",
        """
        def beat(heartbeat_path, payload):
            with open(heartbeat_path, "w") as fh:
                fh.write(payload)
        """,
    )
    assert [f.rule for f in hits] == ["STPU103"]
    # The owning codecs (obs/) are exempt — they implement the atomic
    # tmp + os.replace pattern this rule protects.
    assert (
        _lint_source(
            tmp_path,
            "obs/heartbeat2.py",
            "def beat(heartbeat_path, s):\n"
            '    with open(heartbeat_path, "w") as fh:\n'
            "        fh.write(s)\n",
        )
        == []
    )
    # Reads are fine anywhere.
    assert (
        _lint_source(
            tmp_path,
            "service/reader.py",
            "def read(heartbeat_path):\n"
            '    with open(heartbeat_path, "r") as fh:\n'
            "        return fh.read()\n",
        )
        == []
    )


# --- waiver round-trip ------------------------------------------------------


def test_waiver_round_trip(tmp_path):
    f1 = Finding(
        rule="STPU001", surface="ops:hashset-insert", file="stateright_tpu/ops/hashset.py",
        line=5, message="m", excerpt="e",
    )
    f2 = Finding(
        rule="STPU001", surface="kernel:2pc:3:packed_step", file="stateright_tpu/models/x.py",
        line=9, message="m", excerpt="e",
    )
    wpath = tmp_path / "w.toml"
    wpath.write_text(
        "# comment\n"
        "[[waiver]]\n"
        'rule = "STPU001"\n'
        'surface = "ops:hashset-insert"\n'
        'reason = "by design"\n'
        "\n"
        "[[waiver]]\n"
        'rule = "STPU003"\n'
        'reason = "never matches"\n'
    )
    waivers = load_waivers(str(wpath))
    active, waived, unused = apply_waivers([f1, f2], waivers)
    assert [f.surface for f in active] == ["kernel:2pc:3:packed_step"]
    assert [f.surface for f in waived] == ["ops:hashset-insert"]
    assert waived[0].waiver_reason == "by design"
    assert [w.rule for w in unused] == ["STPU003"]  # stale, reported


def test_waiver_expiry_stops_suppressing(tmp_path):
    """An expired waiver is reported like a stale one and its findings
    go ACTIVE — chip-A/B-pending waivers cannot rot past their window."""
    f = Finding(
        rule="STPU001", surface="ops:hashset-insert",
        file="stateright_tpu/ops/hashset.py", line=5, message="m", excerpt="e",
    )
    wpath = tmp_path / "w.toml"
    wpath.write_text(
        "[[waiver]]\n"
        'rule = "STPU001"\n'
        'surface = "ops:hashset-insert"\n'
        'reason = "pending chip A/B"\n'
        'expires = "2026-01-01"\n'  # past (today is later)
    )
    waivers = load_waivers(str(wpath))
    assert waivers[0].expired
    active, waived, unused = apply_waivers([f], waivers)
    assert [x.surface for x in active] == ["ops:hashset-insert"]
    assert waived == []
    assert unused == waivers  # reported like stale

    # A future expiry still suppresses.
    wpath.write_text(
        "[[waiver]]\n"
        'rule = "STPU001"\n'
        'surface = "ops:hashset-insert"\n'
        'reason = "pending chip A/B"\n'
        'expires = "2099-01-01"\n'
    )
    f2 = Finding(
        rule="STPU001", surface="ops:hashset-insert",
        file="stateright_tpu/ops/hashset.py", line=5, message="m", excerpt="e",
    )
    active, waived, unused = apply_waivers([f2], load_waivers(str(wpath)))
    assert active == [] and len(waived) == 1 and unused == []

    # Garbage dates are loud, not silently never-expiring.
    wpath.write_text(
        '[[waiver]]\nrule = "STPU001"\nreason = "x"\nexpires = "soonish"\n'
    )
    with pytest.raises(WaiverError, match="YYYY-MM-DD"):
        load_waivers(str(wpath))


def test_expired_waiver_reported_in_cli_report(tmp_path):
    """run_lint marks the expired entry even on a partial run (unlike
    merely-stale waivers, an expired one is actionable on ANY run)."""
    wpath = tmp_path / "w.toml"
    wpath.write_text(
        "[[waiver]]\n"
        'rule = "STPU003"\n'
        'reason = "pending chip A/B"\n'
        'expires = "2026-01-01"\n'
    )
    report = run_lint(trace=False, ast_pass=True, waivers_path=str(wpath))
    assert report["partial"] is True  # AST-only run
    expired = [w for w in report["unused_waivers"] if w["expired"]]
    assert [w["rule"] for w in expired] == ["STPU003"]
    assert expired[0]["expires"] == "2026-01-01"


def test_waiver_file_is_loud_on_garbage(tmp_path):
    bad = tmp_path / "w.toml"
    bad.write_text("[[waiver]]\nrule = STPU001\n")  # unquoted value
    with pytest.raises(WaiverError):
        load_waivers(str(bad))
    bad.write_text('[[waiver]]\nrule = "STPU999"\nreason = "x"\n')
    with pytest.raises(WaiverError):
        load_waivers(str(bad))
    bad.write_text('[[waiver]]\nrule = "STPU001"\n')  # no reason
    with pytest.raises(WaiverError):
        load_waivers(str(bad))
    assert load_waivers(str(tmp_path / "missing.toml")) == []


# --- the shipped tree sweeps clean ------------------------------------------


def test_ast_pass_shipped_tree_clean():
    """Whole-package AST pass: every finding is covered by a justified
    waiver in .stpu-lint-waivers.toml."""
    report = run_lint(trace=False, ast_pass=True)
    assert report["errors"] == []
    assert report["findings"] == [], report["findings"]


def test_trace_sweep_shipped_subset_clean():
    """Jaxpr pass over the narrow-model surface subset (the full-tree
    sweep is tools/smoke.sh's lint stage — this keeps the tier-1 pin
    fast): kernels + engine configs + ops + pallas for 2pc, all clean
    under the justified waivers."""
    report = run_lint(
        trace=True, ast_pass=False, only=["2pc:3", "ops:", "pallas:"]
    )
    assert report["errors"] == []
    assert report["findings"] == [], report["findings"]
    # The waivers are LIVE: the hashset scatter and the planes-expand
    # transpose still fire and are waived — a waiver matching nothing
    # would mean the surface moved and the rule went blind.
    waived_rules = {f["rule"] for f in report["waived"]}
    assert {"STPU001", "STPU002"} <= waived_rules


@pytest.mark.slow
def test_full_lint_clean():
    """The complete default sweep (what `python -m stateright_tpu.analysis`
    runs; smoke.sh's lint stage budget-pins it at <60 s)."""
    report = run_lint()
    assert report["errors"] == []
    assert report["findings"] == [], report["findings"]
    assert report["unused_waivers"] == [], report["unused_waivers"]


# --- CLI exit-code-2 paths and the partial contract --------------------------


def test_cli_exit_2_on_malformed_waiver_file(tmp_path, capsys):
    from stateright_tpu.analysis.cli import main

    bad = tmp_path / "w.toml"
    bad.write_text("[[waiver]]\nrule = STPU001\n")  # unquoted value
    rc = main(["--no-trace", "--waivers", str(bad)])
    assert rc == 2
    assert "waiver file error" in capsys.readouterr().err


def test_cli_exit_2_on_surface_trace_failure(monkeypatch, tmp_path):
    """A surface that cannot be TRACED is exit 2 (not verified), never a
    silent pass — and the report's errors list names it."""
    from stateright_tpu.analysis import surfaces
    from stateright_tpu.analysis.cli import main

    def boom():
        raise RuntimeError("golden trace failure")

    monkeypatch.setattr(
        surfaces, "build_sweep", lambda full=False: [("golden:boom", boom)]
    )
    out = tmp_path / "lint.json"
    rc = main(["--no-ast", "--no-cache", "--json-out", str(out)])
    assert rc == 2
    import json as _json

    report = _json.loads(out.read_text())
    assert report["ok"] is False
    assert report["errors"] == ["golden:boom: RuntimeError: golden trace failure"]
    assert report["surfaces"][0]["error"].startswith("RuntimeError")


def test_cli_exit_2_on_unknown_admission_spec(capsys):
    from stateright_tpu.analysis.cli import main

    rc = main(["--admission", "nosuchfamily:3", "--no-cache"])
    assert rc == 2
    assert "unknown model spec" in capsys.readouterr().err


def test_partial_contract_for_lint_ok_provenance(tmp_path, monkeypatch):
    """The contract bench.py's lint_ok tri-state relies on: every
    filtered run is marked partial, and bench treats a partial artifact
    as None (not a pass, not a fail)."""
    report = run_lint(trace=False, ast_pass=True)
    assert report["partial"] is True
    report = run_lint(
        trace=True, ast_pass=False, only=["plan:shipped"], use_cache=False
    )
    assert report["partial"] is True
    report = run_lint(trace=False, ast_pass=True, rules=["STPU101"])
    assert report["partial"] is True

    import bench

    runs = tmp_path / "runs"
    runs.mkdir()
    # A partial artifact -> None, even when fresh and ok.
    (runs / "lint.json").write_text('{"ok": true, "partial": true}')
    monkeypatch.setattr(bench, "RUNS", str(runs))
    monkeypatch.setattr(bench, "REPO", str(tmp_path))  # no newer sources
    assert bench._lint_ok() is None
    # A full artifact -> its verdict.
    (runs / "lint.json").write_text('{"ok": true, "partial": false}')
    assert bench._lint_ok() is True
    (runs / "lint.json").write_text('{"ok": false, "partial": false}')
    assert bench._lint_ok() is False
    # Missing artifact -> None.
    (runs / "lint.json").unlink()
    assert bench._lint_ok() is None


def test_compile_plan_provenance_reads_census(tmp_path, monkeypatch):
    import bench

    runs = tmp_path / "runs"
    runs.mkdir()
    monkeypatch.setattr(bench, "RUNS", str(runs))
    monkeypatch.setattr(bench, "REPO", str(tmp_path))
    assert bench._compile_plan() is None  # no artifact
    (runs / "compile_plan.json").write_text(
        '{"tree": "abc", "specs": {"2pc:3": {"tpu": '
        '{"distinct_programs": 3}}}}'
    )
    plan = bench._compile_plan()
    assert plan == {
        "tree": "abc",
        "distinct_programs": {"2pc:3": {"tpu": 3}},
    }


# --- the content-hash surface cache ------------------------------------------


def test_surface_cache_round_trip(tmp_path):
    """Second run replays findings from the cache (cached=True, same
    findings); --no-cache forces a fresh trace; errors are not cached."""
    from stateright_tpu.analysis.surfaces import run_sweep as sweep

    cold = sweep(only=["plan:shipped"], cache_dir=str(tmp_path))
    assert [r.cached for r in cold] == [False]
    warm = sweep(only=["plan:shipped"], cache_dir=str(tmp_path))
    assert [r.cached for r in warm] == [True]
    assert [f.to_json() for f in warm[0].findings] == [
        f.to_json() for f in cold[0].findings
    ]
    fresh = sweep(only=["plan:shipped"], cache_dir=str(tmp_path), use_cache=False)
    assert [r.cached for r in fresh] == [False]


def test_surface_cache_invalidates_on_tree_change(tmp_path, monkeypatch):
    from stateright_tpu.analysis import cache as cache_mod

    c1 = cache_mod.SurfaceCache(str(tmp_path))
    f = Finding(rule="STPU003", surface="s", file="f.py", line=1,
                message="m", excerpt="e")
    c1.put("s", [f])
    assert [x.message for x in c1.get("s")] == ["m"]
    # A different tree hash misses; the old tree's entries stay warm
    # (within the keep-K bound) for branch switches.
    monkeypatch.setattr(cache_mod, "_tree_hash_memo", "f" * 64)
    c2 = cache_mod.SurfaceCache(str(tmp_path))
    assert c2.get("s") is None
    c2.put("s", [])
    assert "f" * 12 in os.listdir(tmp_path)
    assert c1.dir.split(os.sep)[-1] in os.listdir(tmp_path)


def test_surface_cache_bounds_tree_dirs(tmp_path, monkeypatch):
    """Per-commit tree dirs must not accumulate forever: lint startup
    keeps the newest K (current tree always included), deletes older."""
    import time as time_mod

    from stateright_tpu.analysis import cache as cache_mod

    for i in range(6):
        d = tmp_path / f"{i:012d}"
        d.mkdir()
        (d / "x.json").write_text("{}")
        old = time_mod.time() - (10 - i) * 1000
        os.utime(d, (old, old))
    monkeypatch.setattr(cache_mod, "_tree_hash_memo", "a" * 64)
    cache = cache_mod.SurfaceCache(str(tmp_path), keep_trees=3)
    survivors = sorted(os.listdir(tmp_path))
    # Newest keep-1 == 2 foreign dirs survive next to the current tree.
    assert survivors == ["000000000004", "000000000005"]
    cache.put("s", [])
    assert sorted(os.listdir(tmp_path)) == [
        "000000000004", "000000000005", "a" * 12
    ]
    # STPU_LINT_CACHE_KEEP drives the default.
    monkeypatch.setenv("STPU_LINT_CACHE_KEEP", "1")
    cache_mod.SurfaceCache(str(tmp_path))
    assert sorted(os.listdir(tmp_path)) == ["a" * 12]


# --- SARIF output ------------------------------------------------------------


def test_sarif_output(tmp_path):
    import json as _json

    from stateright_tpu.analysis.cli import write_sarif

    report = run_lint(trace=False, ast_pass=True)
    path = tmp_path / "lint.sarif"
    write_sarif(report, str(path))
    sarif = _json.loads(path.read_text())
    assert sarif["version"] == "2.1.0"
    run = sarif["runs"][0]
    assert run["tool"]["driver"]["name"] == "stpu-lint"
    rule_ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
    assert {"STPU001", "STPU006", "STPU007", "STPU008"} <= rule_ids
    # The shipped tree's waived findings ride as notes with locations.
    notes = [r for r in run["results"] if r["level"] == "note"]
    assert notes, "expected the waived AST findings as SARIF notes"
    assert all(r["ruleId"] in rule_ids for r in run["results"])
    located = [r for r in run["results"] if "locations" in r]
    assert located and all(
        r["locations"][0]["physicalLocation"]["region"]["startLine"] >= 1
        for r in located
    )
