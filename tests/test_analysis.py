"""stpu-lint (stateright_tpu/analysis): every rule ID trips on a
deliberately-bad golden kernel (positive detection), the shipped tree
sweeps clean under the justified waivers, and the waiver file
round-trips.

The golden fixtures are the pinned pathologies rebuilt in miniature —
each one is the exact shape a backend broke on (docs/static-analysis.md
carries the history), so a rule that stops firing here has stopped
guarding the real thing.
"""

import os
import textwrap

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from stateright_tpu.analysis import (
    Finding,
    WaiverError,
    apply_waivers,
    load_waivers,
    run_lint,
)
from stateright_tpu.analysis.astlint import lint_file, run_ast_pass
from stateright_tpu.analysis.jaxpr_lint import (
    cond_flush_sorts,
    mosaic_kernel_rules,
    output_transposes,
    taint_scatters,
    wide_sorts,
)
from stateright_tpu.analysis.surfaces import run_sweep


def _sds(shape, dtype):
    return jax.ShapeDtypeStruct(shape, dtype)


# --- STPU001: data-dependent scatter in a vmapped kernel --------------------


def test_stpu001_flags_traced_index_scatter(monkeypatch):
    import stateright_tpu.packing as packing

    monkeypatch.setattr(packing, "ONE_HOT_WRITES", True)

    def bad(words, i):  # the round-3/5 paxos-drift shape
        return words.at[i].set(jnp.uint32(1))

    jx = jax.make_jaxpr(jax.vmap(bad))(
        _sds((4096, 8), jnp.uint32), _sds((4096,), jnp.uint32)
    )
    hits = taint_scatters(jx, "golden:stpu001")
    assert [f.rule for f in hits] == ["STPU001"]
    assert "stateright_tpu" not in hits[0].file  # anchored to THIS file
    assert hits[0].line > 0


def test_stpu001_static_index_scatter_is_exempt():
    def ok(words):  # static-index write: XLA folds it, drift never repro'd
        return words.at[3].set(jnp.uint32(1))

    jx = jax.make_jaxpr(jax.vmap(ok))(_sds((4096, 8), jnp.uint32))
    assert taint_scatters(jx, "golden:static") == []


def test_stpu001_word_update_path_is_clean(monkeypatch):
    """The sanctioned lowering (packing._word_update under the
    accelerator pin) emits no scatter at all — the generalized form of
    the old test_packing HLO pin."""
    import stateright_tpu.packing as packing
    from stateright_tpu.packing import LayoutBuilder

    monkeypatch.setattr(packing, "ONE_HOT_WRITES", True)
    lay = LayoutBuilder().array("xs", 6, 4).finish()

    def good(words, i):
        return lay.set(words, "xs", 3, i)

    jx = jax.make_jaxpr(jax.vmap(good))(
        _sds((4096, lay.words), jnp.uint32), _sds((4096,), jnp.uint32)
    )
    assert taint_scatters(jx, "golden:word-update") == []


# --- STPU002: transpose fused into a vmapped kernel -------------------------


def test_stpu002_flags_out_axes_transpose():
    def kernel(words):
        return words * jnp.uint32(2)

    jx = jax.make_jaxpr(jax.vmap(kernel, out_axes=1))(_sds((64, 4), jnp.uint32))
    hits = output_transposes(jx, "golden:stpu002")
    assert [f.rule for f in hits] == ["STPU002"]
    assert "transpose" in hits[0].excerpt

    clean = jax.make_jaxpr(jax.vmap(kernel))(_sds((64, 4), jnp.uint32))
    assert output_transposes(clean, "golden:rows") == []


# --- STPU003: the wide-W sort compile-stall shape ---------------------------


def test_stpu003_flags_wide_sort():
    W = 25  # paxos width: the round-5 stall was its W+3-operand sort

    def bad(*lanes):
        return jax.lax.sort(lanes, num_keys=1)

    args = [_sds((1024,), jnp.uint32) for _ in range(W + 3)]
    jx = jax.make_jaxpr(bad)(*args)
    hits = wide_sorts(jx, "golden:stpu003")
    assert [f.rule for f in hits] == ["STPU003"]
    assert "28-operand" in hits[0].message

    ok = jax.make_jaxpr(bad)(*args[:12])  # the chip-proven W<=8 class
    assert wide_sorts(ok, "golden:narrow") == []


# --- STPU004: deltaset flush under lax.cond ---------------------------------


def test_stpu004_flags_flush_under_cond():
    from stateright_tpu.ops import deltaset

    ds = deltaset.make(1 << 13, jnp)

    def bad(ds, pred):  # the round-5 "TPU worker crashed" shape
        return jax.lax.cond(
            pred, lambda d: deltaset.maintain(d)[0], lambda d: d, ds
        )

    jx = jax.make_jaxpr(bad)(ds, _sds((), jnp.bool_))
    hits = cond_flush_sorts(jx, "golden:stpu004", ds.main_capacity)
    assert hits and all(f.rule == "STPU004" for f in hits)
    assert "ops/deltaset.py" in hits[0].file

    # The host-invoked form (the shipped protocol) is clean.
    ok = jax.make_jaxpr(deltaset.maintain)(ds)
    assert cond_flush_sorts(ok, "golden:maintain", ds.main_capacity) == []


# --- STPU005: Mosaic TC kernel rules ----------------------------------------


def _pallas_jaxpr(kernel, n=256):
    from jax.experimental import pallas as pl

    def run(x):
        return pl.pallas_call(
            kernel, out_shape=jax.ShapeDtypeStruct((n,), jnp.int32)
        )(x)

    return jax.make_jaxpr(run)(_sds((n,), jnp.int32))


def test_stpu005_flags_cumsum_in_kernel():
    def bad_kernel(x_ref, o_ref):  # the r5e first-silicon lowering gap
        o_ref[...] = jnp.cumsum(x_ref[...])

    hits = mosaic_kernel_rules(_pallas_jaxpr(bad_kernel), "golden:cumsum")
    assert hits and all(f.rule == "STPU005" for f in hits)
    assert "cumsum" in hits[0].message


def test_stpu005_flags_u32_f32_cast_in_kernel():
    def bad_kernel(x_ref, o_ref):
        f = x_ref[...].astype(jnp.uint32).astype(jnp.float32)  # direct cast
        o_ref[...] = f.astype(jnp.int32)

    hits = mosaic_kernel_rules(_pallas_jaxpr(bad_kernel), "golden:cast")
    assert any("u32<->f32" in f.message for f in hits)


def test_stpu005_i32_hop_is_clean():
    def ok_kernel(x_ref, o_ref):  # the sanctioned value-exact hop
        f = x_ref[...].astype(jnp.float32)
        o_ref[...] = f.astype(jnp.int32)

    assert mosaic_kernel_rules(_pallas_jaxpr(ok_kernel), "golden:hop") == []


def test_stpu005_shipped_kernels_preflight_for_tpu():
    """Registry #6 as one command: both ops/ pallas kernels lower for
    the TPU target from this CPU-only process (this is the check that
    caught the integer-reduction Mosaic gap in both kernels)."""
    reports = {r.name: r for r in run_sweep(only=["pallas:"])}
    assert set(reports) == {"pallas:compact", "pallas:merge"}
    for rep in reports.values():
        assert rep.error == "", rep.error
        assert rep.findings == [], [f.message for f in rep.findings]


# --- AST rules (STPU101-103) ------------------------------------------------


def _lint_source(tmp_path, rel, text):
    p = tmp_path / rel
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(text))
    return lint_file(str(p), rel)


def test_stpu101_flags_at_write_in_models(tmp_path):
    hits = _lint_source(
        tmp_path,
        "models/bad_model.py",
        """
        def packed_step(self, words, i):
            return words.at[i].set(1)
        """,
    )
    assert [f.rule for f in hits] == ["STPU101"]
    assert ".at[i].set(1)" in hits[0].excerpt
    # The same write outside models/ is not this rule's business.
    assert (
        _lint_source(tmp_path, "ops/fine.py", "def f(w, i):\n    return w.at[i].set(1)\n")
        == []
    )


def test_stpu102_flags_bare_backend_bringup(tmp_path):
    hits = _lint_source(
        tmp_path, "cli_helper.py", "import jax\nds = jax.devices()\n"
    )
    assert [f.rule for f in hits] == ["STPU102"]
    # backend.py owns the guarded paths.
    assert (
        _lint_source(tmp_path, "backend.py", "import jax\nds = jax.devices()\n")
        == []
    )


def test_stpu103_flags_raw_heartbeat_write(tmp_path):
    hits = _lint_source(
        tmp_path,
        "service/sloppy.py",
        """
        def beat(heartbeat_path, payload):
            with open(heartbeat_path, "w") as fh:
                fh.write(payload)
        """,
    )
    assert [f.rule for f in hits] == ["STPU103"]
    # The owning codecs (obs/) are exempt — they implement the atomic
    # tmp + os.replace pattern this rule protects.
    assert (
        _lint_source(
            tmp_path,
            "obs/heartbeat2.py",
            "def beat(heartbeat_path, s):\n"
            '    with open(heartbeat_path, "w") as fh:\n'
            "        fh.write(s)\n",
        )
        == []
    )
    # Reads are fine anywhere.
    assert (
        _lint_source(
            tmp_path,
            "service/reader.py",
            "def read(heartbeat_path):\n"
            '    with open(heartbeat_path, "r") as fh:\n'
            "        return fh.read()\n",
        )
        == []
    )


# --- waiver round-trip ------------------------------------------------------


def test_waiver_round_trip(tmp_path):
    f1 = Finding(
        rule="STPU001", surface="ops:hashset-insert", file="stateright_tpu/ops/hashset.py",
        line=5, message="m", excerpt="e",
    )
    f2 = Finding(
        rule="STPU001", surface="kernel:2pc:3:packed_step", file="stateright_tpu/models/x.py",
        line=9, message="m", excerpt="e",
    )
    wpath = tmp_path / "w.toml"
    wpath.write_text(
        "# comment\n"
        "[[waiver]]\n"
        'rule = "STPU001"\n'
        'surface = "ops:hashset-insert"\n'
        'reason = "by design"\n'
        "\n"
        "[[waiver]]\n"
        'rule = "STPU003"\n'
        'reason = "never matches"\n'
    )
    waivers = load_waivers(str(wpath))
    active, waived, unused = apply_waivers([f1, f2], waivers)
    assert [f.surface for f in active] == ["kernel:2pc:3:packed_step"]
    assert [f.surface for f in waived] == ["ops:hashset-insert"]
    assert waived[0].waiver_reason == "by design"
    assert [w.rule for w in unused] == ["STPU003"]  # stale, reported


def test_waiver_file_is_loud_on_garbage(tmp_path):
    bad = tmp_path / "w.toml"
    bad.write_text("[[waiver]]\nrule = STPU001\n")  # unquoted value
    with pytest.raises(WaiverError):
        load_waivers(str(bad))
    bad.write_text('[[waiver]]\nrule = "STPU999"\nreason = "x"\n')
    with pytest.raises(WaiverError):
        load_waivers(str(bad))
    bad.write_text('[[waiver]]\nrule = "STPU001"\n')  # no reason
    with pytest.raises(WaiverError):
        load_waivers(str(bad))
    assert load_waivers(str(tmp_path / "missing.toml")) == []


# --- the shipped tree sweeps clean ------------------------------------------


def test_ast_pass_shipped_tree_clean():
    """Whole-package AST pass: every finding is covered by a justified
    waiver in .stpu-lint-waivers.toml."""
    report = run_lint(trace=False, ast_pass=True)
    assert report["errors"] == []
    assert report["findings"] == [], report["findings"]


def test_trace_sweep_shipped_subset_clean():
    """Jaxpr pass over the narrow-model surface subset (the full-tree
    sweep is tools/smoke.sh's lint stage — this keeps the tier-1 pin
    fast): kernels + engine configs + ops + pallas for 2pc, all clean
    under the justified waivers."""
    report = run_lint(
        trace=True, ast_pass=False, only=["2pc:3", "ops:", "pallas:"]
    )
    assert report["errors"] == []
    assert report["findings"] == [], report["findings"]
    # The waivers are LIVE: the hashset scatter and the planes-expand
    # transpose still fire and are waived — a waiver matching nothing
    # would mean the surface moved and the rule went blind.
    waived_rules = {f["rule"] for f in report["waived"]}
    assert {"STPU001", "STPU002"} <= waived_rules


@pytest.mark.slow
def test_full_lint_clean():
    """The complete default sweep (what `python -m stateright_tpu.analysis`
    runs; smoke.sh's lint stage budget-pins it at <60 s)."""
    report = run_lint()
    assert report["errors"] == []
    assert report["findings"] == [], report["findings"]
    assert report["unused_waivers"] == [], report["unused_waivers"]
