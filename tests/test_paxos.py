"""Paxos example parity tests.

Oracle: the reference's own test ``can_model_paxos`` asserts 16,668 unique
states at 2 clients / 3 servers on an unordered non-duplicating network and
an 8-action witness for "value chosen" (examples/paxos.rs:294-346), for both
BFS and DFS.
"""

import pytest

from stateright_tpu.actor import register as reg
from stateright_tpu.actor.model import DeliverAction
from stateright_tpu.models.paxos import paxos_model


def _check(spawn, shortest_witness: bool):
    model = paxos_model(client_count=2, server_count=3)
    checker = spawn(model.checker()).join()
    checker.assert_properties()
    assert checker.unique_state_count() == 16_668
    witness = checker.discoveries()["value chosen"]
    pairs = witness.into_vec()
    actions = [a for _s, a in pairs if a is not None]
    if shortest_witness:
        # BFS finds the 8-action shortest witness (examples/paxos.rs:311-320).
        assert len(actions) == 8
        assert isinstance(actions[0].msg, reg.Put)
        assert isinstance(actions[-1].msg, reg.Get)
    assert all(isinstance(a, DeliverAction) for a in actions)
    final = pairs[-1][0]
    assert any(
        isinstance(env.msg, reg.GetOk) and env.msg.value is not None
        for env in final.network.iter_deliverable()
    )


@pytest.mark.slow
def test_can_model_paxos_bfs():
    _check(lambda b: b.spawn_bfs(), shortest_witness=True)


@pytest.mark.slow
def test_can_model_paxos_dfs():
    _check(lambda b: b.spawn_dfs(), shortest_witness=False)
