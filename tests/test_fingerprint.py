"""Stable-fingerprint unit tests (reference contract: lib.rs:327-336 fixed-key
hashing; util.rs:134-156 order-insensitive container hashing)."""

from stateright_tpu import fingerprint


def test_stability_and_distinctness():
    assert fingerprint((1, 2)) == fingerprint((1, 2))
    assert fingerprint((1, 2)) != fingerprint((2, 1))
    assert fingerprint(0) != 0  # nonzero contract
    # Types don't collide structurally.
    assert fingerprint(1) != fingerprint("1") != fingerprint((1,))
    assert fingerprint(True) != fingerprint(1)
    assert fingerprint([1, 2]) != fingerprint((1, 2))


def test_order_insensitive_containers():
    assert fingerprint({1, 2, 3}) == fingerprint({3, 1, 2})
    assert fingerprint(frozenset({1, 2})) == fingerprint({2, 1})
    assert fingerprint({"a": 1, "b": 2}) == fingerprint({"b": 2, "a": 1})
    assert fingerprint({1, 2}) != fingerprint({1, 2, 3})


def test_large_and_negative_ints_do_not_collide_mod_2_64():
    assert fingerprint(0) != fingerprint(1 << 64)
    assert fingerprint(-1) != fingerprint((1 << 64) - 1)
    assert fingerprint(1 << 200) != fingerprint(1 << 201)
    assert fingerprint(-(1 << 70)) != fingerprint(1 << 70)


def test_dataclasses_and_enums():
    from dataclasses import dataclass
    from enum import Enum

    @dataclass(frozen=True)
    class S:
        x: int
        y: tuple

    class E(Enum):
        A = 1
        B = 2

    assert fingerprint(S(1, (2,))) == fingerprint(S(1, (2,)))
    assert fingerprint(S(1, (2,))) != fingerprint(S(2, (2,)))
    assert fingerprint(E.A) != fingerprint(E.B)
    assert fingerprint(E.A) != fingerprint(1)
