"""Utility-container tests: VectorClock (ported from the reference's own
suite, vector_clock.rs:109-275) and DenseNatMap (densenatmap.rs:98-113,
223-238), plus a model-level consumer — a vector-clock variant of the
reference's logical-clock doc example (actor.rs:11-79) whose counterexample
exercises increment/merge/partial-order inside a checked actor system.
"""

import pytest

from stateright_tpu.fingerprint import fingerprint
from stateright_tpu.utils.densenatmap import DenseNatMap
from stateright_tpu.utils.rewrite_plan import RewritePlan, rewrite
from stateright_tpu.utils.vector_clock import VectorClock

# --- VectorClock (vector_clock.rs:109-275) --------------------------------


def test_can_display():
    assert str(VectorClock([1, 2, 3, 4])) == "<1, 2, 3, 4, ...>"
    # Notably equal vectors don't necessarily display the same.
    assert str(VectorClock([])) == "<...>"
    assert str(VectorClock([0])) == "<...>"  # zero suffix trimmed at build


def test_can_equate():
    assert VectorClock() == VectorClock()
    assert VectorClock([0]) == VectorClock([])
    assert VectorClock([]) == VectorClock([0])
    assert VectorClock([]) != VectorClock([1])
    assert VectorClock([1]) != VectorClock([])


def test_can_hash():
    # same hash if equal
    assert hash(VectorClock()) == hash(VectorClock())
    assert hash(VectorClock([])) == hash(VectorClock([0, 0]))
    assert hash(VectorClock([1])) == hash(VectorClock([1, 0]))
    assert fingerprint(VectorClock([1])) == fingerprint(VectorClock([1, 0]))
    # otherwise hash varies w/ high probability
    assert hash(VectorClock([])) != hash(VectorClock([1]))
    assert fingerprint(VectorClock([])) != fingerprint(VectorClock([1]))


def test_can_increment():
    assert VectorClock().incremented(2) == VectorClock([0, 0, 1])
    assert VectorClock().incremented(2).incremented(0).incremented(2) == VectorClock(
        [1, 0, 2]
    )


def test_can_merge():
    assert VectorClock([1, 2, 3, 4]).merge_max(VectorClock([5, 6, 0])) == VectorClock(
        [5, 6, 3, 4]
    )
    assert VectorClock([1, 0, 2]).merge_max(VectorClock([3, 1, 0, 4])) == VectorClock(
        [3, 1, 2, 4]
    )


def test_can_order_partially():
    # Clocks with matching elements are equal; missing elements are zero.
    assert VectorClock([]).partial_cmp(VectorClock([])) == 0
    assert VectorClock([]).partial_cmp(VectorClock([0, 0])) == 0
    assert VectorClock([0, 0]).partial_cmp(VectorClock([])) == 0
    assert VectorClock([1, 2, 0]).partial_cmp(VectorClock([1, 2])) == 0
    # Less: at least one element less, the rest <=.
    assert VectorClock([]).partial_cmp(VectorClock([1])) == -1
    assert VectorClock([1, 2, 3]).partial_cmp(VectorClock([1, 3, 4])) == -1
    assert VectorClock([1, 2, 3]).partial_cmp(VectorClock([1, 3, 3])) == -1
    assert VectorClock([1, 2, 3]).partial_cmp(VectorClock([2, 3, 3])) == -1
    assert VectorClock([1, 2, 3]) < VectorClock([2, 3, 3])
    # Greater: at least one element greater, the rest >=.
    assert VectorClock([1]).partial_cmp(VectorClock([])) == 1
    assert VectorClock([1, 2, 3]).partial_cmp(VectorClock([1, 1, 2])) == 1
    assert VectorClock([1, 2, 3]).partial_cmp(VectorClock([1, 1, 3])) == 1
    assert VectorClock([1, 2, 4]).partial_cmp(VectorClock([0, 1, 3])) == 1
    assert VectorClock([1, 2, 4]) > VectorClock([0, 1, 3])
    # Incomparable when mixed.
    assert VectorClock([1, 2, 3]).partial_cmp(VectorClock([1, 3, 2])) is None
    assert VectorClock([1, 2, 3]).partial_cmp(VectorClock([3, 2, 1])) is None
    assert VectorClock([1, 2, 2]).partial_cmp(VectorClock([2, 1, 2])) is None
    assert not VectorClock([1, 2, 3]) < VectorClock([1, 3, 2])
    assert not VectorClock([1, 2, 3]) > VectorClock([1, 3, 2])


# --- DenseNatMap (densenatmap.rs:98-113, 223-238) -------------------------


def test_dense_insert_and_lookup():
    m = DenseNatMap()
    m.insert(0, "a")
    m.insert(1, "b")
    m[1] = "B"  # overwrite in place
    assert m[0] == "a" and m[1] == "B"
    assert len(m) == 2
    assert list(m.items()) == [(0, "a"), (1, "B")]
    assert m.get(5) is None


def test_insert_at_gap_raises():
    m = DenseNatMap(["a"])
    with pytest.raises(IndexError):
        m.insert(2, "c")  # key 1 missing — keys must be dense


def test_eq_hash_fingerprint():
    assert DenseNatMap(["x", "y"]) == DenseNatMap(["x", "y"])
    assert DenseNatMap(["x", "y"]) != DenseNatMap(["y", "x"])
    assert hash(DenseNatMap(["x"])) == hash(DenseNatMap(["x"]))
    assert fingerprint(DenseNatMap(["x"])) == fingerprint(DenseNatMap(["x"]))


def test_rewrite_reindexes_by_plan():
    """The reference's DenseNatMap Rewrite impl reindexes via the plan
    (densenatmap.rs:223-238); RewritePlan itself stores its inverse in a
    DenseNatMap (rewrite_plan.rs:19)."""
    plan = RewritePlan.from_values_to_sort(["c", "a", "b"])
    assert plan.order == [1, 2, 0]
    assert isinstance(plan.new_of_old, DenseNatMap)
    m = DenseNatMap(["c", "a", "b"])
    assert rewrite(m, plan) == DenseNatMap(["a", "b", "c"])


# --- model-level consumer: vector-clock actors ----------------------------


class VectorClockActor:
    """The reference's logical-clock doc actor (actor.rs:11-79) with a
    VectorClock state: merge-and-increment on receive, reply while the
    received clock dominates ours."""

    def __init__(self, index, bootstrap_to_id=None):
        self.index = index
        self.bootstrap_to_id = bootstrap_to_id

    def on_start(self, id, out):
        if self.bootstrap_to_id is not None:
            clock = VectorClock().incremented(self.index)
            out.send(self.bootstrap_to_id, clock)
            return clock
        return VectorClock()

    def on_msg(self, id, state, src, msg, out):
        if isinstance(msg, VectorClock) and msg.partial_cmp(state.get()) == 1:
            merged = state.get().merge_max(msg).incremented(self.index)
            state.set(merged)
            out.send(src, merged)

    def on_timeout(self, id, state, timer, out):
        pass


def test_vector_clock_actor_model_counterexample():
    """Two actors bounce merged clocks; the false claim that no actor's own
    component reaches 3 is disproved in exactly 4 deliveries."""
    from stateright_tpu.actor import ActorModel, Id, Network
    from stateright_tpu.core import Expectation

    model = (
        ActorModel(cfg=None)
        .actor(VectorClockActor(0))
        .actor(VectorClockActor(1, bootstrap_to_id=Id(0)))
        .init_network(Network.new_unordered_duplicating())
        .property(
            Expectation.ALWAYS,
            "less than max",
            lambda _m, s: all(
                clock.get(i) < 3 for i, clock in enumerate(s.actor_states)
            ),
        )
    )
    checker = model.checker().spawn_bfs().join()
    witness = checker.discoveries()["less than max"]
    pairs = witness.into_vec()
    actions = [a for _s, a in pairs if a is not None]
    assert len(actions) == 4
    final = pairs[-1][0]
    assert final.actor_states == (VectorClock([2, 2]), VectorClock([2, 3]))
