"""The two-tier delta visited set (ops/deltaset.py): op-level differential
parity against the flat sorted set and the hash set, flush behavior, tier
invariants, and engine-level parity of ``spawn_xla(dedup="delta")``.

The delta structure exists for soak-scale tables (per-level cost bounded
by the delta tier + binary search instead of a full-capacity sort); its
contract is identical, so every test here is an equality test.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from stateright_tpu.ops import deltaset, hashset, sortedset


def _insert_with_flush(dl, hi, lo, vh, vl, act):
    """Drive deltaset.insert under its round-5 contract: delta-full
    reports overflow, the caller flushes (maintain) and retries — the
    same protocol the engine's _resolve_table_overflow runs."""
    out, is_new, ovf = deltaset.insert(dl, hi, lo, vh, vl, act)
    if not bool(ovf):
        return out, is_new
    flushed, f_ovf = deltaset.maintain(dl)
    assert not bool(f_ovf), "flush cannot fit main"
    out, is_new, ovf = deltaset.insert(flushed, hi, lo, vh, vl, act)
    assert not bool(ovf), "batch alone overflows the delta tier"
    return out, is_new


def _rand_batch(rng, m, universe):
    hi = jnp.asarray(rng.integers(1, universe, m, dtype=np.uint32))
    lo = jnp.asarray(rng.integers(1, universe, m, dtype=np.uint32))
    vh = jnp.asarray(rng.integers(0, 2**32, m, dtype=np.uint32))
    vl = jnp.asarray(rng.integers(0, 2**32, m, dtype=np.uint32))
    act = jnp.asarray(rng.integers(0, 2, m).astype(bool))
    return hi, lo, vh, vl, act


@pytest.mark.parametrize("universe", [40, 2**31])  # heavy duplicates / near-unique
def test_insert_lookup_differential_vs_other_structures(universe):
    rng = np.random.default_rng(11)
    dl = deltaset.make(1 << 11, jnp)
    ss = sortedset.make(1 << 12, jnp)
    hs = hashset.make(1 << 13, jnp)
    for rnd in range(10):
        hi, lo, vh, vl, act = _rand_batch(rng, 257, universe)
        dl, d_new = _insert_with_flush(dl, hi, lo, vh, vl, act)
        ss, s_new, s_ovf = sortedset.insert(ss, hi, lo, vh, vl, act)
        hs, h_new, h_ovf = hashset.insert(hs, hi, lo, vh, vl, act)
        assert np.array_equal(np.asarray(d_new), np.asarray(s_new)), rnd
        assert np.array_equal(np.asarray(d_new), np.asarray(h_new)), rnd
        assert not bool(s_ovf)
        qh = jnp.asarray(rng.integers(1, min(universe + 20, 2**32 - 1), 128, dtype=np.uint32))
        ql = jnp.asarray(rng.integers(1, min(universe + 20, 2**32 - 1), 128, dtype=np.uint32))
        for a, b in zip(deltaset.lookup(dl, qh, ql), sortedset.lookup(ss, qh, ql)):
            assert np.array_equal(np.asarray(a), np.asarray(b)), rnd


def test_flush_fires_and_preserves_membership():
    """Batches sized to overflow the delta tier force the flush-and-retry
    protocol; every inserted key must remain a member and tier
    invariants hold."""
    rng = np.random.default_rng(5)
    # main 2^12 -> delta tier 1024: two 700-unique batches must flush.
    dl = deltaset.make(1 << 12, jnp)
    seen = set()
    for rnd in range(4):
        hi, lo, vh, vl, act = _rand_batch(rng, 700, 2**31)
        dl, is_new = _insert_with_flush(dl, hi, lo, vh, vl, act)
        a = np.asarray(act)
        for h, l, keep in zip(np.asarray(hi), np.asarray(lo), a):
            if keep:
                seen.add((int(h), int(l)))
    assert int(dl.n_main) > 0, "flush never fired"
    # Tier invariants: sorted unique prefixes, zero pads, disjoint tiers.
    for kh_p, kl_p, n in (
        (dl.main_key_hi, dl.main_key_lo, int(dl.n_main)),
        (dl.delta_key_hi, dl.delta_key_lo, int(dl.n_delta)),
    ):
        kh = np.asarray(kh_p)
        kl = np.asarray(kl_p)
        keys = (kh[:n].astype(np.uint64) << 32) | kl[:n]
        assert np.all(keys[1:] > keys[:-1])
        assert not np.any(kh[n:]) and not np.any(kl[n:])
    assert int(dl.n_main) + int(dl.n_delta) == len(seen)
    qh = jnp.asarray(np.asarray([k[0] for k in seen], np.uint32))
    ql = jnp.asarray(np.asarray([k[1] for k in seen], np.uint32))
    found, _, _ = deltaset.lookup(dl, qh, ql)
    assert bool(jnp.all(found))


def test_grow_rebuilds_both_tiers():
    rng = np.random.default_rng(7)
    dl = deltaset.make(1 << 11, jnp)
    hi, lo, vh, vl, act = _rand_batch(rng, 500, 2**31)
    dl, _, _ = deltaset.insert(dl, hi, lo, vh, vl, act)
    n_before = int(dl.n_main) + int(dl.n_delta)
    grown = deltaset.grow(dl, 1 << 13, jnp)
    assert grown.main_capacity == 1 << 13
    assert int(grown.n_main) == n_before and int(grown.n_delta) == 0
    found, gvh, gvl = deltaset.lookup(
        grown, jnp.where(act, hi, 1), jnp.where(act, lo, 1)
    )
    # every active key is a member of the grown set
    assert bool(jnp.all(jnp.where(act, found, True)))


def _counts(c):
    return (c.state_count(), c.unique_state_count(), c.max_depth())


def test_engine_parity_dedup_delta():
    """spawn_xla(dedup="delta") reproduces the sorted engine's counts and
    witness paths, including through in-kernel flushes (small tiers)."""
    from stateright_tpu.models.two_phase_commit import PackedTwoPhaseSys

    kw = dict(frontier_capacity=1 << 6, table_capacity=1 << 10)
    a = PackedTwoPhaseSys(3).checker().spawn_xla(dedup="sorted", **kw).join()
    b = PackedTwoPhaseSys(3).checker().spawn_xla(dedup="delta", **kw).join()
    assert _counts(a) == _counts(b)
    assert b.unique_state_count() == 288
    da, db = a.discoveries(), b.discoveries()
    assert set(da) == set(db) and da
    for name in da:
        assert da[name].into_states() == db[name].into_states()


def test_engine_parity_delta_under_forced_growth():
    from stateright_tpu.models.two_phase_commit import PackedTwoPhaseSys

    kw = dict(frontier_capacity=1 << 6, table_capacity=1 << 7)
    a = PackedTwoPhaseSys(4).checker().spawn_xla(dedup="hash", **kw).join()
    b = PackedTwoPhaseSys(4).checker().spawn_xla(dedup="delta", **kw).join()
    assert _counts(a) == _counts(b)
    assert b.unique_state_count() == 1_568


def test_checkpoint_crosses_into_delta(tmp_path):
    from stateright_tpu.models.two_phase_commit import PackedTwoPhaseSys

    path = str(tmp_path / "ck.npz")
    a = PackedTwoPhaseSys(3).checker().spawn_xla(
        dedup="sorted", levels_per_dispatch=1
    )
    for _ in range(4):
        a._run_block()
    a.save_checkpoint(path)
    b = PackedTwoPhaseSys(3).checker().spawn_xla(
        dedup="delta", checkpoint=path
    ).join()
    full = PackedTwoPhaseSys(3).checker().spawn_xla(dedup="delta").join()
    assert _counts(b) == _counts(full) == (1146, 288, 11)


def test_engine_parity_delta_symmetry():
    from stateright_tpu.models.increment import PackedIncrement

    a = PackedIncrement(3).checker().symmetry().spawn_xla(dedup="sorted").join()
    b = PackedIncrement(3).checker().symmetry().spawn_xla(dedup="delta").join()
    assert _counts(a) == _counts(b) == (27, 17, 5)


def test_delta_insert_values_via_sort_matches_gather(monkeypatch):
    """deltaset's prologue sort mirrors sortedset's values lowering
    (payload-through-sort on accelerators vs post-sort gathers on CPU):
    both must produce bit-identical tiers, is_new, and overflow."""
    rng = np.random.default_rng(31)
    dl_a = deltaset.make(1 << 11, jnp)
    dl_b = deltaset.make(1 << 11, jnp)
    for rnd in range(6):
        hi, lo, vh, vl, act = _rand_batch(rng, 257, 300)
        monkeypatch.setattr(sortedset, "VALUES_VIA", "gather")
        dl_a, new_a, ovf_a = deltaset.insert(dl_a, hi, lo, vh, vl, act)
        monkeypatch.setattr(sortedset, "VALUES_VIA", "sort")
        dl_b, new_b, ovf_b = deltaset.insert(dl_b, hi, lo, vh, vl, act)
        for a, b in zip(dl_a, dl_b):
            assert np.array_equal(np.asarray(a), np.asarray(b)), rnd
        assert np.array_equal(np.asarray(new_a), np.asarray(new_b)), rnd
        assert not bool(ovf_a) and not bool(ovf_b), rnd
    # The overflow leg, for real: a shrunken delta tier (the module knob
    # exists for exactly this) that one near-unique batch overflows. Both
    # lowerings must report it; the returned sets are discarded per the
    # contract.
    monkeypatch.setattr(deltaset, "MIN_DELTA", 128)
    hi, lo, vh, vl, act = _rand_batch(rng, 257, 2**31)
    small_a = deltaset.make(1 << 11, jnp)
    small_b = deltaset.make(1 << 11, jnp)
    monkeypatch.setattr(sortedset, "VALUES_VIA", "gather")
    _, new_a, ovf_a = deltaset.insert(small_a, hi, lo, vh, vl, act)
    monkeypatch.setattr(sortedset, "VALUES_VIA", "sort")
    _, new_b, ovf_b = deltaset.insert(small_b, hi, lo, vh, vl, act)
    assert bool(ovf_a) and bool(ovf_b)
    assert np.array_equal(np.asarray(new_a), np.asarray(new_b))


def test_engine_delta_flushes_during_tail_shrink(monkeypatch):
    """rm=5 with a 256-row delta tier forces many host-invoked flushes
    while the fused loop's tail shrink-exit is downshifting buckets —
    the two dispatch-boundary mechanisms must compose without losing
    exactness. Pins both: exact counts AND an observed downshift."""
    from test_ladder import assert_tail_downshift

    from stateright_tpu.models.two_phase_commit import PackedTwoPhaseSys

    # 2^14 >> 6 = 256: both knobs patched so the tier STARTS at 256 rows
    # (MIN_DELTA alone would be outrun by the default shift's 1024). A
    # 256-row tier cannot hold rm=5's peak-level winners (~2.3k), so the
    # run must also exercise the empty-delta-overflow growth cascade
    # until the tier fits a level.
    monkeypatch.setattr(deltaset, "DELTA_SHIFT", 6)
    monkeypatch.setattr(deltaset, "MIN_DELTA", 256)
    c = (
        PackedTwoPhaseSys(5)
        .checker()
        .spawn_xla(dedup="delta", frontier_capacity=1 << 13, table_capacity=1 << 14)
        .join()
    )
    assert (c.state_count(), c.unique_state_count(), c.max_depth()) == (
        58_146,
        8_832,
        17,
    )
    # Keys reach main only through a flush: flushes fired.
    assert int(c._table.n_main) > 0
    # And the tier was grown past its starting 256 rows by the cascade.
    assert c._table.delta_capacity > 256
    assert_tail_downshift(c.dispatch_log)


def test_delta_insert_packed_keys_match_pair(monkeypatch):
    """The u64 key-packing knob reaches all three of deltaset's sorts
    (prologue, delta merge, maintain) — bit-identical to the pair
    lowering, never a silent fallback."""
    import jax

    monkeypatch.setattr(sortedset, "VALUES_VIA", "sort")
    rng = np.random.default_rng(47)
    dl_a = deltaset.make(1 << 11, jnp)
    dl_b = deltaset.make(1 << 11, jnp)
    prev_x64 = jax.config.jax_enable_x64
    jax.config.update("jax_enable_x64", True)
    try:
        for rnd in range(6):
            hi, lo, vh, vl, act = _rand_batch(rng, 257, 300)
            monkeypatch.setattr(sortedset, "KEYS_VIA", "pair")
            dl_a, new_a, ovf_a = deltaset.insert(dl_a, hi, lo, vh, vl, act)
            monkeypatch.setattr(sortedset, "KEYS_VIA", "packed")
            dl_b, new_b, ovf_b = deltaset.insert(dl_b, hi, lo, vh, vl, act)
            for a, b in zip(dl_a, dl_b):
                assert np.array_equal(np.asarray(a), np.asarray(b)), rnd
            assert np.array_equal(np.asarray(new_a), np.asarray(new_b)), rnd
            assert bool(ovf_a) == bool(ovf_b)
        fa, _ = deltaset.maintain(dl_a)
        monkeypatch.setattr(sortedset, "KEYS_VIA", "pair")
        fb, _ = deltaset.maintain(dl_b)
        for a, b in zip(fa, fb):
            assert np.array_equal(np.asarray(a), np.asarray(b))
    finally:
        jax.config.update("jax_enable_x64", prev_x64)
