"""The crash-recovery stack under fault injection (ISSUE 8 acceptance):

- SIGKILL a supervised worker at a random superstep; the supervisor
  relaunches it RESUMING from the latest valid checkpoint rotation, and
  the final unique/generated counts and discoveries are bit-identical to
  an uninterrupted run — on two packed models under both the single-chip
  and the sharded engine (CPU backend).
- SIGSTOP (frozen heartbeat mid-"dispatch" — the wedged-tunnel signature)
  is detected by heartbeat staleness, the process group is killed, and the
  resumed run still converges exactly.
- A truncated/torn checkpoint raises the typed ``CheckpointCorrupt`` (not
  a zipfile traceback) and the supervisor's resume resolution falls back
  to the previous rotation automatically.

The worker body is ``tests/chaos_worker.py``; supervision is the real
library (``stateright_tpu/supervise.py``) — the same code bench.py and
tools/soak.py run."""

import json
import os
import random
import sys

import pytest

from stateright_tpu import checkpoint as ck_mod
from stateright_tpu import supervise as sup
from stateright_tpu.parallel import default_mesh

WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)), "chaos_worker.py")

#: Pinned full-coverage (generated, unique) counts (bench.py EXPECTED_*).
PINNED = {
    "2pc3": (1_146, 288),
    "2pc4": (8_258, 1_568),
    "scr31": (6_778, 4_243),
}


def _build(spec):
    if spec.startswith("2pc"):
        from stateright_tpu.models.two_phase_commit import PackedTwoPhaseSys

        return PackedTwoPhaseSys(int(spec[3:])), dict(
            frontier_capacity=1 << 10, table_capacity=1 << 13
        )
    from stateright_tpu.models.single_copy_register import (
        PackedSingleCopyRegister,
    )

    return PackedSingleCopyRegister(3, 1), dict(
        frontier_capacity=1 << 11, table_capacity=1 << 14
    )


_REF_CACHE = {}


def _reference(spec, engine):
    """Uninterrupted in-process run of the same (model, engine) — the
    ground truth the supervised chaos run must reproduce bit-for-bit.
    Discoveries compare per engine: the mesh's pmax witness election is a
    documented divergence from the single-chip frontier order."""
    key = (spec, engine)
    if key not in _REF_CACHE:
        model, kw = _build(spec)
        if engine == "sharded":
            kw = dict(kw, mesh=default_mesh())
        c = model.checker().spawn_xla(**kw).join()
        _REF_CACHE[key] = {
            "generated": c.state_count(),
            "unique": c.unique_state_count(),
            "max_depth": c.max_depth(),
            "discoveries": {
                name: [repr(a) for a in path.into_actions()]
                for name, path in sorted(c.discoveries().items())
            },
        }
    return _REF_CACHE[key]


def _supervised_chaos(tmp_path, spec, engine, chaos_flag, depth, *,
                      retries=2, stall_s=1200.0):
    ck = str(tmp_path / "ck.npz")
    out = str(tmp_path / "result.json")
    marker = str(tmp_path / "chaos.marker")

    def make_argv(attempt, resume):
        argv = [
            sys.executable, WORKER,
            "--model", spec, "--engine", engine,
            "--checkpoint", ck, "--out", out,
            "--every", "1", "--keep", "3",
            "--chaos-marker", marker,
            chaos_flag, str(depth),
        ]
        if resume:
            argv += ["--resume", resume]
        return argv

    res = sup.supervise(
        make_argv,
        checkpoint=ck,
        retries=retries,
        backoff_s=0.1,
        heartbeat=str(tmp_path / "hb.json"),
        timeout_s=600,
        stall_s=stall_s,
        startup_grace_s=300,
        poll_s=0.5,
        stdout_path=lambda attempt: str(tmp_path / f"worker{attempt}.out"),
    )
    assert res.ok, [(a.rc, a.killed) for a in res.attempts]
    assert os.path.exists(marker), "chaos never tripped"
    with open(out) as fh:
        return res, json.load(fh)


def _assert_exact(result, spec, engine):
    ref = _reference(spec, engine)
    assert (result["generated"], result["unique"]) == PINNED[spec]
    assert result["generated"] == ref["generated"]
    assert result["unique"] == ref["unique"]
    assert result["max_depth"] == ref["max_depth"]
    assert result["discoveries"] == ref["discoveries"]


# --- SIGKILL at a random superstep, both engines, two packed models -------


@pytest.mark.parametrize(
    "spec,engine",
    [
        ("2pc4", "single"),
        ("2pc4", "sharded"),
        ("scr31", "single"),
        ("scr31", "sharded"),
    ],
)
def test_sigkill_resume_exact(tmp_path, spec, engine):
    depth = random.randint(3, 6)  # a random superstep mid-space
    res, result = _supervised_chaos(
        tmp_path, spec, engine, "--die-at-depth", depth
    )
    # The first attempt died (SIGKILL = -9); a later attempt resumed from a
    # checkpoint (with per-level cadence the latest one is AT the kill
    # depth — zero levels replayed) and converged exactly.
    assert res.attempts[0].rc == -9
    assert len(res.attempts) >= 2
    assert res.resumed_from[-1] is not None
    assert result["resumed_from"] == res.resumed_from[-1]
    assert result["start_depth"] == depth
    _assert_exact(result, spec, engine)


# --- SIGSTOP: frozen heartbeat mid-dispatch = wedged tunnel ---------------


def test_sigstop_wedge_detected_and_resumed(tmp_path):
    depth = random.randint(3, 6)
    # stall_s=10: a frozen beat in phase="dispatch" goes stale past the
    # leash and the supervisor must kill the (unkillable-by-SIGTERM,
    # SIGSTOP-frozen) process group and relaunch. Compile-carrying beats
    # get a 3x leash, so healthy first-dispatch compiles survive.
    res, result = _supervised_chaos(
        tmp_path, "2pc4", "single", "--freeze-at-depth", depth, stall_s=10.0
    )
    assert res.attempts[0].killed is not None
    assert "stale" in res.attempts[0].killed
    assert res.resumed_from[-1] is not None
    assert result["start_depth"] == depth
    _assert_exact(result, "2pc4", "single")


# --- torn checkpoint: typed error + automatic rotation fallback -----------


def test_truncated_checkpoint_typed_error_and_fallback(tmp_path):
    from stateright_tpu.models.two_phase_commit import PackedTwoPhaseSys

    ck = str(tmp_path / "ck.npz")
    partial = PackedTwoPhaseSys(4).checker().spawn_xla(
        frontier_capacity=1 << 10, table_capacity=1 << 13,
        levels_per_dispatch=1,
    )
    for _ in range(3):
        partial._run_block()
    partial.save_checkpoint(ck, keep=3)
    partial._run_block()
    partial.save_checkpoint(ck, keep=3)  # rotates the depth-4 file to .1

    # Truncate the newest rotation mid-file — a torn write from a crashed
    # foreign writer. Detection must be the TYPED error, not a zipfile
    # traceback…
    size = os.path.getsize(ck)
    with open(ck, "r+b") as fh:
        fh.truncate(size // 2)
    with pytest.raises(ck_mod.CheckpointCorrupt):
        ck_mod.load_checkpoint(ck)

    # …and the supervisor's resume resolution falls back to the previous
    # rotation automatically.
    assert ck_mod.latest_valid_checkpoint(ck) == ck + ".1"

    seen = []

    def make_argv(attempt, resume):
        seen.append(resume)
        return [sys.executable, "-c", "pass"]

    res = sup.supervise(make_argv, checkpoint=ck, retries=0, poll_s=0.2)
    assert res.ok
    assert seen == [ck + ".1"]

    # The fallback rotation resumes to the exact pinned counts.
    resumed = PackedTwoPhaseSys(4).checker().spawn_xla(
        frontier_capacity=1 << 10, table_capacity=1 << 13,
        checkpoint=ck + ".1",
    ).join()
    assert resumed.state_count() == 8_258
    assert resumed.unique_state_count() == 1_568


# --- fast kill-and-resume smoke (tools/smoke.sh) --------------------------


def test_smoke_kill_resume(tmp_path):
    """The <30s tier-0 crash drill: one SIGKILL, one supervised resume,
    exact pinned counts on the smallest packed model."""
    res, result = _supervised_chaos(
        tmp_path, "2pc3", "single", "--die-at-depth", 3, retries=1
    )
    assert res.attempts[0].rc == -9
    assert res.resumed_from[-1] is not None
    assert (result["generated"], result["unique"]) == PINNED["2pc3"]
    assert result["checkpoints_written"] >= 1
