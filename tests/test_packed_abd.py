"""Packed ABD register on the device engine.

Oracle: the reference's own test asserts 544 unique states at 2 clients /
2 servers on an unordered non-duplicating network, both BFS and DFS
(linearizable-register.rs:289,316). Same guardrails as the packed Paxos:
exact codec round-trips plus action-for-action differential parity against
the object model, then end-to-end equality on ``spawn_xla``.
"""

import random

import numpy as np
import pytest

from stateright_tpu.actor.network import Envelope
from stateright_tpu.models.linearizable_register import (
    PackedAbd,
    linearizable_register_model,
)


def test_codec_round_trips_and_differential_step_parity():
    import jax
    import jax.numpy as jnp

    m = PackedAbd(2, 2)
    rng = random.Random(11)
    init = m._inner.init_states()[0]
    sample = {init}
    cur = init
    for _ in range(4000):
        steps = list(m._inner.next_steps(cur))
        if not steps:
            cur = init
            continue
        _, cur = rng.choice(steps)
        sample.add(cur)
        if len(sample) >= 150:
            break
    states = sorted(sample, key=repr)

    packed = np.stack([m.pack(s) for s in states])
    for s, row in zip(states, packed):
        assert m.unpack(row) == s, f"codec round-trip mismatch for {s!r}"

    nxt, valid, ovf = jax.jit(jax.vmap(m.packed_step))(jnp.asarray(packed))
    nxt, valid, ovf = np.asarray(nxt), np.asarray(valid), np.asarray(ovf)
    assert not ovf.any(), "codec overflow on reachable states"

    for si, s in enumerate(states):
        obj = {}
        for action, ns in m._inner.next_steps(s):
            code = m._env_code[Envelope(action.src, action.dst, action.msg)]
            obj[code] = ns
        assert set(np.nonzero(valid[si])[0].tolist()) == set(obj), (
            f"enabled-action mismatch at state {si}"
        )
        for code, ns in obj.items():
            np.testing.assert_array_equal(
                nxt[si, code],
                m.pack(ns),
                err_msg=f"successor mismatch: state {si}, envelope {m._envs[code]!r}",
            )


def test_xla_matches_the_544_state_oracle():
    m = PackedAbd(2, 2)
    xc = m.checker().spawn_xla(
        frontier_capacity=1 << 10,
        table_capacity=1 << 12,
        host_verified_cap=1024,
    ).join()
    assert xc.unique_state_count() == 544  # linearizable-register.rs:289,316
    xc.assert_properties()
    # The reachability witness replays through the object model.
    path = xc.discoveries()["value chosen"]
    final = path.last_state()
    assert any(
        type(env.msg).__name__ == "GetOk" and env.msg.value is not None
        for env in final.network.iter_deliverable()
    )


def test_non_oracle_sizes_fall_back_to_host_engines():
    with pytest.raises(ValueError):
        PackedAbd(2, 3)  # S != 2: quorum arithmetic is single-peer
    with pytest.raises(ValueError):
        PackedAbd(4, 2)
    # The object model still checks any size on the host engines.
    c = linearizable_register_model(2, 2).checker().spawn_bfs().join()
    assert c.unique_state_count() == 544


def test_three_client_codec_and_step_parity():
    import jax
    import jax.numpy as jnp

    m = PackedAbd(3, 2)
    rng = random.Random(7)
    init = m._inner.init_states()[0]
    sample = {init}
    cur = init
    for _ in range(8000):
        steps = list(m._inner.next_steps(cur))
        if not steps:
            cur = init
            continue
        _, cur = rng.choice(steps)
        sample.add(cur)
        if len(sample) >= 120:
            break
    states = sorted(sample, key=repr)
    packed = np.stack([m.pack(s) for s in states])
    for s, row in zip(states, packed):
        assert m.unpack(row) == s
    nxt, valid, ovf = jax.jit(jax.vmap(m.packed_step))(jnp.asarray(packed))
    nxt, valid, ovf = np.asarray(nxt), np.asarray(valid), np.asarray(ovf)
    assert not ovf.any()
    for si, s in enumerate(states):
        want = {m.pack(ns).tobytes() for _, ns in m._inner.next_steps(s)}
        got = {
            nxt[si, a].tobytes() for a in range(m.max_actions) if valid[si, a]
        }
        assert got == want, f"step mismatch at state {si}"


@pytest.mark.slow
def test_three_client_full_check_parity():
    # ABD at 3 clients / 2 servers with EXACT device linearizability over
    # the 3-thread interleaving enumeration (1,680 patterns/state). The
    # pinned counts are this package's host-oracle result (spawn_bfs on
    # linearizable_register_model(3, 2): 68,115 generated / 35,009 unique /
    # depth 37 — the reference has no oracle for this size).
    c = (
        PackedAbd(3, 2)
        .checker()
        .spawn_xla(frontier_capacity=1 << 12, table_capacity=1 << 16)
        .join()
    )
    c.assert_properties()
    assert (c.state_count(), c.unique_state_count(), c.max_depth()) == (
        68115,
        35009,
        37,
    )
