"""Live UDP Paxos cluster: three servers + a driver client on loopback.

The model checker proves the protocol; this proves the *runtime* — the same
PaxosActor that model-checks to 16,668 states binds real sockets, reaches
quorum, decides a value, and serves a linearizable read, end to end in
seconds. Also a regression test for the wire codec: Paxos ballots carry
``Id`` values inside tuples (paxos.rs protocol messages), which must
round-trip through the JSON codec.
"""

import threading

from stateright_tpu.actor import Id
from stateright_tpu.actor import register as reg
from stateright_tpu.actor.spawn import json_codec, spawn
from stateright_tpu.models.paxos import (
    Accept,
    Accepted,
    Decided,
    PaxosActor,
    Prepare,
    Prepared,
)


class Driver:
    """Puts a value, then Gets it back, with resend-on-timeout robustness
    (loopback UDP is reliable in practice; the timer guards CI flakes)."""

    def __init__(self, server, record, done):
        self.server = server
        self.record = record
        self.done = done

    def on_start(self, id, out):
        out.set_timer("kick", (0.05, 0.05))
        return "put"

    def on_timeout(self, id, state, timer, out):
        phase = state.get()
        if phase == "put":
            out.send(self.server, reg.Put(1, "X"))
        elif phase == "get":
            out.send(self.server, reg.Get(2))
        if phase != "done":
            out.set_timer("kick", (0.5, 0.5))

    def on_msg(self, id, state, src, msg, out):
        if isinstance(msg, reg.PutOk) and state.get() == "put":
            state.set("get")
            out.send(self.server, reg.Get(2))
        elif isinstance(msg, reg.GetOk) and state.get() == "get":
            self.record.append(msg.value)
            state.set("done")
            out.cancel_timer("kick")
            self.done.set()


def test_live_paxos_cluster_decides_and_serves_reads():
    base = 28500
    ids = [Id.from_addr("127.0.0.1", base + i) for i in range(4)]
    servers, client = ids[:3], ids[3]
    serialize, deserialize = json_codec(
        reg.Put, reg.Get, reg.PutOk, reg.GetOk, reg.Internal,
        Prepare, Prepared, Accept, Accepted, Decided,
    )
    record: list = []
    done = threading.Event()
    handles = spawn(
        serialize,
        deserialize,
        [(i, PaxosActor([x for x in servers if x != i])) for i in servers]
        + [(client, Driver(servers[0], record, done))],
        background=True,
    )
    try:
        assert done.wait(timeout=15), "cluster failed to decide within 15s"
        assert record == ["X"]
    finally:
        for _thread, runtime in handles:
            runtime.stopped.set()
        for thread, _runtime in handles:
            thread.join(timeout=5)
