"""Visited-set integrity audit (stateright_tpu/audit.py).

The audit is the instrument for the round-3 on-chip paxos count drift
(BASELINE.md): a duplicate fingerprint in the table means the device insert
admitted an already-present key. On a healthy backend the audit must come
back clean for every visited-set structure and both device engines, with
``entries == unique_state_count()``.
"""

from stateright_tpu.audit import audit_table
from stateright_tpu.models.paxos import PackedPaxos
from stateright_tpu.models.two_phase_commit import PackedTwoPhaseSys


def _assert_clean(checker, expected_unique):
    report = audit_table(checker)
    assert report["ok"], report
    assert report["duplicate_keys"] == 0, report
    assert report["entries"] == expected_unique == report["unique_count"], report


def test_audit_clean_all_structures_single_chip():
    for dedup in ("hash", "sorted", "delta"):
        c = (
            PackedTwoPhaseSys(3)
            .checker()
            .spawn_xla(frontier_capacity=1 << 8, table_capacity=1 << 10, dedup=dedup)
        )
        c.join()
        assert c.unique_state_count() == 288, dedup
        _assert_clean(c, 288)


def test_audit_clean_after_growth():
    # Mid-run table growth is the prime suspect window for lost/duplicated
    # entries: start the table far too small so every structure grows.
    for dedup in ("hash", "sorted", "delta"):
        c = (
            PackedPaxos(2, 2)
            .checker()
            .spawn_xla(frontier_capacity=1 << 8, table_capacity=1 << 7, dedup=dedup)
        )
        c.join()
        _assert_clean(c, c.unique_state_count())


def test_audit_clean_sharded_engine():
    from stateright_tpu.parallel import default_mesh

    c = (
        PackedTwoPhaseSys(3)
        .checker()
        .spawn_xla(
            mesh=default_mesh(8),
            frontier_capacity=1 << 9,
            table_capacity=1 << 10,
            dedup="sorted",
        )
    )
    c.join()
    assert c.unique_state_count() == 288
    _assert_clean(c, 288)
