"""The host-verified sampling cliff (5 clients: first config past
MAX_PATTERNS_EXACT, VERDICT r4 weak #6).

At 5 clients x 2 ops the interleaving enumeration is 1.68e8 patterns, so
the single-copy register drops to the sampled one-sided device pass +
exact host confirmation (``host_verified_properties``). These tests pin
the contract at that cliff:

- the sampled path still FINDS real violations (5c/2s has the stale-read
  counterexample every smaller shape has, single-copy-register.rs:136);
- the telemetry (``checker.hv_stats``) obeys the one-sided accounting
  (checked = cleared + confirmed, checked <= flagged);
- ``pattern_limit`` is a real knob (the model accepts it and threads it
  into the device pass).

The full characterization (flag rate and host share vs pattern_limit on
a bounded 5c/1s run) is ``tools/hv_cliff.py`` — too slow for CI.
"""

import pytest

from stateright_tpu.models.single_copy_register import PackedSingleCopyRegister
from stateright_tpu.semantics.device import MAX_PATTERNS_EXACT, pattern_count


def test_five_clients_is_past_the_exact_budget():
    assert pattern_count(5, PackedSingleCopyRegister.MAX_OPS) > MAX_PATTERNS_EXACT
    model = PackedSingleCopyRegister(5, 1, pattern_limit=256)
    assert model.host_verified_properties == {"linearizable"}
    assert model._pattern_limit == 256


@pytest.mark.slow
def test_sampled_path_finds_the_5c2s_violation():
    model = PackedSingleCopyRegister(5, 2, pattern_limit=256)
    checker = model.checker().spawn_xla(
        frontier_capacity=1 << 12,
        table_capacity=1 << 15,
        host_verified_cap=1 << 12,
    )
    while not checker.is_done():
        checker._run_block()
    # The ALWAYS property "linearizable" must have a confirmed violation.
    assert checker.discovery("linearizable") is not None
    stats = checker.hv_stats
    assert stats["confirmed"] >= 1
    assert stats["host_checked"] == stats["cleared"] + stats["confirmed"]
    assert stats["host_checked"] <= stats["flagged"]
    assert stats["host_sec"] > 0.0
