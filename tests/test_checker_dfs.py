"""DFS engine oracle tests, ported from /root/reference/src/checker/dfs.rs:454-624."""

from stateright_tpu import Model, PathRecorder, Property, StateRecorder
from stateright_tpu.test_util import Guess, LinearEquation


def test_visits_states_in_dfs_order():
    recorder, accessor = StateRecorder.new_with_accessor()
    LinearEquation(2, 10, 14).checker().visitor(recorder).spawn_dfs().join()
    assert accessor() == [(0, y) for y in range(28)]


def test_can_complete_by_enumerating_all_states():
    checker = LinearEquation(2, 4, 7).checker().spawn_dfs().join()
    assert checker.is_done()
    checker.assert_no_discovery("solvable")
    assert checker.unique_state_count() == 256 * 256


def test_can_complete_by_eliminating_properties():
    checker = LinearEquation(2, 10, 14).checker().spawn_dfs().join()
    checker.assert_properties()
    assert checker.unique_state_count() == 55

    # DFS found this example... (2*0 + 10*27) % 256 == 14
    assert checker.discovery("solvable").into_actions() == [Guess.INCREASE_Y] * 27
    # ... but there are other solutions.
    checker.assert_discovery(
        "solvable", [Guess.INCREASE_X, Guess.INCREASE_Y, Guess.INCREASE_X]
    )


class _Sys(Model):
    """Symmetry-reduction regression model (dfs.rs:536-623).

    Processes advance Loading -> Running -> (Paused <-> Running).  A buggy
    symmetry implementation that enqueues the representative (rather than the
    original state) collects invalid paths; PathRecorder's reconstruction
    raises on such paths.  Encoded as state tuples of ints with
    Paused < Loading < Running to mirror the reference's derived ordering.
    """

    PAUSED, LOADING, RUNNING = 0, 1, 2

    def init_states(self):
        return [(self.LOADING, self.LOADING)]

    def actions(self, state, actions):
        actions.extend([0, 1])

    def next_state(self, state, action):
        procs = list(state)
        p = procs[action]
        procs[action] = self.RUNNING if p in (self.LOADING, self.PAUSED) else self.PAUSED
        return tuple(procs)

    def properties(self):
        return [
            Property.always("visit all states", lambda _, s: True),
            Property.sometimes(
                "a process pauses",
                lambda _, s: s[0] == _Sys.PAUSED or s[1] == _Sys.PAUSED,
            ),
        ]


def test_can_apply_symmetry_reduction():
    # 9 states without symmetry reduction.
    assert _Sys().checker().spawn_dfs().join().unique_state_count() == 9
    assert _Sys().checker().spawn_bfs().join().unique_state_count() == 9

    # 6 states with symmetry reduction; PathRecorder raises on invalid paths.
    visitor, _accessor = PathRecorder.new_with_accessor()
    checker = (
        _Sys()
        .checker()
        .symmetry_fn(lambda s: tuple(sorted(s)))
        .visitor(visitor)
        .spawn_dfs()
        .join()
    )
    assert checker.unique_state_count() == 6
