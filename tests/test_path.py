"""Path reconstruction tests, ported from /root/reference/src/checker/path.rs:223-256
and checker.rs:643-667."""

import pytest

from stateright_tpu import NondeterministicModelError, Path, fingerprint
from stateright_tpu.test_util import FnModel, LinearEquation


def test_can_build_path_from_fingerprints():
    model = LinearEquation(2, 10, 14)
    fp = lambda a, b: fingerprint((a, b))
    fingerprints = [fp(0, 0), fp(0, 1), fp(1, 1), fp(2, 1)]
    path = Path.from_fingerprints(model, fingerprints)
    assert path.last_state() == (2, 1)
    assert path.last_state() == Path.final_state(model, fingerprints)


def test_panics_if_unable_to_reconstruct_init_state():
    def fn(prev, out):
        if prev is None:
            out.append("UNEXPECTED")

    with pytest.raises(NondeterministicModelError):
        Path.from_fingerprints(FnModel(fn), [fingerprint("expected")])


def test_panics_if_unable_to_reconstruct_next_state():
    def fn(prev, out):
        out.append("expected" if prev is None else "UNEXPECTED")

    with pytest.raises(NondeterministicModelError):
        Path.from_fingerprints(
            FnModel(fn), [fingerprint("expected"), fingerprint("expected")]
        )


def test_encode_and_from_actions():
    model = LinearEquation(2, 10, 14)
    from stateright_tpu.test_util import Guess

    path = Path.from_actions(model, (0, 0), [Guess.INCREASE_X, Guess.INCREASE_Y])
    assert path.into_states() == [(0, 0), (1, 0), (1, 1)]
    assert path.into_actions() == [Guess.INCREASE_X, Guess.INCREASE_Y]
    assert len(path.encode().split("/")) == 3
    # Unreachable inputs return None.
    assert Path.from_actions(model, (9, 9), [Guess.INCREASE_X]) is None
