"""OpenMetrics export + the Explorer telemetry endpoints (ISSUE 13):

- the renderer/parser round-trip (``stateright_tpu/obs/promexport.py``):
  ``# TYPE`` discipline, counter ``_total`` suffixes, label escaping, the
  ``# EOF`` terminator — and the parser REJECTS malformed expositions, so
  the smoke stage's scrape is a real validation, not a string match;
- label sets stable across the three dedup structures (a Prometheus
  scraper must see one schema whether a job ran hash/sorted/delta);
- ``GET /.metrics`` served end-to-end against a live service-backed
  Explorer with every counter cross-checked against ``checker.metrics()``
  EXACTLY, plus the windowed ``GET /.jobs/{id}/metrics.json`` series and
  the ``/.dash`` dashboard assets — through a real HTTP socket;
- a batch job's recorded per-job series (``service/worker.py`` sampling
  at quiescent boundaries into the job dir) served back through the pool.

``test_smoke_metrics_endpoint`` (<30 s) rides in tools/smoke.sh.
"""

import json
import threading
import urllib.request

import pytest

from stateright_tpu.checker.explorer import _ExplorerHandler, make_app
from stateright_tpu.models.two_phase_commit import PackedTwoPhaseSys
from stateright_tpu.obs import promexport as pe
from stateright_tpu.service import CheckerService, ServiceConfig

KW = dict(frontier_capacity=1 << 8, table_capacity=1 << 10)

#: ONE shared model instance (the test_obs.py pattern): compiled
#: supersteps cache on the model, so every spawn after the first reuses
#: the XLA programs instead of paying a fresh compile.
MODEL = PackedTwoPhaseSys(2)
#: 2pc rm=2 full-coverage counts (host oracle; bench.py pins rm>=3).
EXPECTED = (154, 56)


def _service(tmp_path, **kw):
    base = dict(
        run_dir=str(tmp_path / "svc"),
        platform="cpu",
        stall_s=8.0,
        startup_grace_s=240.0,
        poll_s=0.2,
        probe_auto=False,
        admission_lint=False,
    )
    base.update(kw)
    return CheckerService(ServiceConfig(**base))


# --- renderer / parser ----------------------------------------------------


def test_render_parse_round_trip():
    samples = [
        ("stpu_state_count_total", {"engine": "xla", "dedup": "sorted"}, 154.0),
        ("stpu_table_occupancy", {"engine": "xla", "dedup": "sorted"}, 0.0546875),
        ("stpu_pool_queued", {}, 3.0),
        # Label values needing escapes survive the round trip.
        ("stpu_frontier_count", {"job": 'we"ird\nname\\x'}, 7.0),
    ]
    text = pe.render_openmetrics(samples)
    assert text.endswith("# EOF\n")
    lines = text.splitlines()
    assert "# TYPE stpu_state_count counter" in lines
    assert "# TYPE stpu_table_occupancy gauge" in lines
    parsed = pe.parse_openmetrics(text)
    assert len(parsed) == len(samples)
    for name, labels, value in samples:
        assert parsed[(name, frozenset(labels.items()))] == pytest.approx(value)


def test_build_info_sample_renders_and_memoizes():
    """The stpu_build_info gauge: value 1, arbitrary label keys render
    through the OpenMetrics writer, and the expensive labels (jax
    version + package tree hash) compute once per process."""
    s1 = pe.build_info_sample(platform="tpu")
    name, labels, value = s1
    assert name == "stpu_build_info" and value == 1.0
    assert labels["platform"] == "tpu"
    assert {"jax", "tree"} <= set(labels)
    parsed = pe.parse_openmetrics(pe.render_openmetrics([s1]))
    assert parsed[(name, frozenset(labels.items()))] == 1.0
    s2 = pe.build_info_sample(platform="cpu")
    # Same memoized identity labels, only the platform differs.
    assert {k: v for k, v in s2[1].items() if k != "platform"} == \
        {k: v for k, v in labels.items() if k != "platform"}


def test_parser_rejects_malformed():
    ok = pe.render_openmetrics([("stpu_depth", {"engine": "xla"}, 4.0)])
    # Missing terminator.
    with pytest.raises(ValueError, match="EOF"):
        pe.parse_openmetrics(ok.replace("# EOF\n", ""))
    # A sample with no preceding # TYPE.
    with pytest.raises(ValueError, match="TYPE"):
        pe.parse_openmetrics("stpu_x 1\n# EOF")
    # Counter family sample without the _total suffix.
    with pytest.raises(ValueError, match="_total"):
        pe.parse_openmetrics(
            "# TYPE stpu_x counter\nstpu_x 1\n# EOF"
        )
    # Unparseable value.
    with pytest.raises(ValueError, match="value"):
        pe.parse_openmetrics(
            "# TYPE stpu_x gauge\nstpu_x banana\n# EOF"
        )
    # Duplicate sample (same name + label set).
    with pytest.raises(ValueError, match="duplicate"):
        pe.parse_openmetrics(
            "# TYPE stpu_x gauge\nstpu_x 1\nstpu_x 2\n# EOF"
        )


def _counter_names(parsed):
    return {name for name, _ in parsed if name.endswith("_total")}


@pytest.mark.parametrize("dedup", ["hash", "sorted", "delta"])
def test_label_and_family_sets_stable_across_dedups(dedup):
    c = MODEL.checker().spawn_xla(dedup=dedup, **KW).join()
    assert (c.state_count(), c.unique_state_count()) == EXPECTED
    m = c.metrics()
    parsed = pe.parse_openmetrics(
        pe.render_openmetrics(pe.engine_samples(m, {"job": "j1"}))
    )
    # Every sample carries exactly the identity triple, with the dedup
    # label tracking the structure.
    for (_name, labels) in parsed:
        assert dict(labels) == {"job": "j1", "engine": "xla", "dedup": dedup}
    # The family set is dedup-independent (one scraper schema): pin the
    # core families every structure must expose.
    names = {name for name, _ in parsed}
    assert {
        "stpu_state_count_total", "stpu_unique_state_count_total",
        "stpu_dispatches_total", "stpu_levels_committed_total",
        "stpu_table_grows_total", "stpu_delta_flushes_total",
        "stpu_checkpoints_written_total", "stpu_frontier_count",
        "stpu_table_capacity", "stpu_table_occupancy", "stpu_depth",
        "stpu_hv_flagged",
    } <= names, names
    if not hasattr(test_label_and_family_sets_stable_across_dedups, "_names"):
        test_label_and_family_sets_stable_across_dedups._names = names
    assert names == test_label_and_family_sets_stable_across_dedups._names


# --- Explorer endpoints ---------------------------------------------------


def _exact_cross_check(parsed, m, job_label):
    """Every counter the exposition claims for this job matches
    checker.metrics() EXACTLY (the acceptance criterion)."""
    labels = frozenset(
        {("job", job_label), ("engine", m["engine"]), ("dedup", m["dedup"])}
    )
    checked = 0
    for key in pe.COUNTER_KEYS:
        if key not in m:
            continue
        assert parsed[(f"stpu_{key}_total", labels)] == m[key], key
        checked += 1
    assert checked >= 10
    assert parsed[("stpu_table_occupancy", labels)] == pytest.approx(
        m["table_occupancy"]
    )
    return checked


def test_smoke_metrics_endpoint(tmp_path):
    """The smoke-stage drill (tools/smoke.sh): one packed model run with
    the recorder on, ``/.metrics`` scraped from a make_app instance,
    validated with the parser, counters cross-checked exactly."""
    from stateright_tpu.obs import read_series

    svc = _service(tmp_path)
    try:
        app, checker = make_app(
            MODEL.checker(), service=svc,
            metrics_to=str(tmp_path / "metrics.jsonl"), metrics_every=1,
            **KW,
        )
        try:
            checker.run_to_completion()
            for _ in range(64):
                if checker.is_done():
                    break
                app.drive(10_000)
            assert checker.is_done()
            assert (checker.state_count(), checker.unique_state_count()) == EXPECTED
            # The recorder sampled the interactive run at quiescent
            # boundaries.
            rows = read_series(str(tmp_path / "metrics.jsonl"))
            assert rows and rows[-1]["metrics"]["state_count"] == EXPECTED[0]
            # Scrape + validate + exact cross-check.
            m = checker.metrics()
            parsed = pe.parse_openmetrics(app.metrics_text())
            job_id = app.status()["job"]
            _exact_cross_check(parsed, m, job_id)
            # Pool families ride alongside (this session occupies one
            # interactive slot).
            assert parsed[("stpu_pool_interactive", frozenset())] == 1
            assert parsed[("stpu_pool_breaker_open", frozenset())] == 0
            # Build-info gauge: value 1, platform/jax/tree labels (the
            # tree hash ties a scrape to the package the lint cache
            # keyed — which code produced these numbers).
            build = [
                (labels, v) for (fam, labels), v in parsed.items()
                if fam == "stpu_build_info"
            ]
            assert len(build) == 1
            labels, v = build[0]
            assert v == 1
            keys = dict(labels)
            assert {"platform", "jax", "tree"} <= set(keys)
            assert keys["platform"] == "cpu"
            assert len(keys["tree"]) == 12
            # The windowed per-job series endpoint serves the live ring.
            code, body = app.job_metrics(job_id, window=16)
            assert code == 200
            assert body["rows"][-1]["metrics"]["state_count"] == EXPECTED[0]
        finally:
            app.close()
    finally:
        svc.close()


def test_metrics_endpoint_without_service():
    # make_app always builds a default pool; ExplorerApp without one is
    # the embedder path — construct it directly.
    from stateright_tpu.checker.explorer import ExplorerApp

    checker = MODEL.checker().spawn_xla(**KW)
    bare = ExplorerApp(checker)
    parsed = pe.parse_openmetrics(bare.metrics_text())
    labels = frozenset(
        {("job", "interactive"), ("engine", "xla"),
         ("dedup", checker.metrics()["dedup"])}
    )
    assert ("stpu_state_count_total", labels) in parsed
    # No pool families without a service; the live ring serves under the
    # "interactive" id and 404s anything else.
    assert not any(n.startswith("stpu_pool_") for n, _ in parsed)
    assert bare.job_metrics("interactive")[0] == 200
    assert bare.job_metrics("nope")[0] == 404
    # A zero/negative window clamps to 1 — it must not bypass the cap
    # and stream the whole series in one poll.
    code, body = bare.job_metrics("interactive", window=-5)
    assert code == 200 and body["window"] == 1 and len(body["rows"]) == 1
    # The live ring's row seq is strictly monotonic across polls (the
    # recorder row contract), not the ring length.
    seqs = [
        bare.job_metrics("interactive")[1]["rows"][-1]["seq"]
        for _ in range(3)
    ]
    assert seqs == sorted(seqs) and len(set(seqs)) == 3


def test_fleet_metrics_per_device_rows_no_double_count(tmp_path):
    """A fleet /.metrics exposition renders the pool families ONLY as
    per-device labeled rows — an unlabeled aggregate repeating them
    would make a PromQL ``sum`` over the family double-count — while
    fleet-scoped state (the fleet counters, which fire before any pool
    is touched; the fleet breaker verdict; fleet.jsonl position) exports
    under its own ``stpu_fleet_*`` families."""
    from stateright_tpu.service import FleetConfig, FleetService

    fleet = FleetService(FleetConfig(
        run_dir=str(tmp_path / "fleet"),
        devices=2,
        pool=ServiceConfig(
            platform="cpu", max_inflight=0,
            probe_auto=False, admission_lint=False,
        ),
    ))
    try:
        fleet.submit("2pc:3", idempotency_key="m1")
        fleet.submit("2pc:3", idempotency_key="m1")  # fleet-level dedup
        app, checker = make_app(MODEL.checker(), service=fleet, **KW)
        try:
            parsed = pe.parse_openmetrics(app.metrics_text())
            dev0 = frozenset({("device", "device-0")})
            dev1 = frozenset({("device", "device-1")})
            assert ("stpu_pool_queued", dev0) in parsed
            assert ("stpu_pool_queued", dev1) in parsed
            # No unlabeled duplicate of a per-device family: the family
            # sum IS the truth (one queued batch job fleet-wide).
            assert ("stpu_pool_queued", frozenset()) not in parsed
            assert sum(
                v for (n, labs), v in parsed.items()
                if n == "stpu_pool_queued"
            ) == 1
            assert sum(
                v for (n, labs), v in parsed.items()
                if n == "stpu_pool_interactive"
            ) == 1  # this session, on exactly one device
            # Fleet-scoped rows render under their own families — incl.
            # the counters no per-device row can carry (the fleet-level
            # idempotency dedup never reached a pool).
            assert parsed[("stpu_fleet_routed_total", frozenset())] == 1
            assert parsed[("stpu_fleet_idem_dedups_total", frozenset())] == 1
            assert parsed[("stpu_fleet_submitted_total", frozenset())] >= 2
            assert parsed[("stpu_fleet_device_count", frozenset())] == 2
            assert ("stpu_fleet_breaker_open", frozenset()) in parsed
            assert ("stpu_pool_breaker_open", dev0) in parsed
            # The aggregated occupancy sums are NOT re-exported under
            # stpu_fleet_* either (derivable from the per-device rows).
            assert not any(
                n == "stpu_fleet_queued" for n, _ in parsed
            )
        finally:
            app.close()
    finally:
        fleet.close()


def test_http_end_to_end(tmp_path):
    """The real socket path: /.metrics content type + parse, the
    dashboard assets, and the windowed series endpoint with ?n=."""
    from http.server import ThreadingHTTPServer

    svc = _service(tmp_path)
    app, checker = make_app(MODEL.checker(), service=svc, **KW)

    class Handler(_ExplorerHandler):
        explorer_app = app

    server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    port = server.server_address[1]
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    try:
        def get(path):
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=30
            ) as res:
                return res.status, res.headers.get("Content-Type"), res.read()

        status, ctype, body = get("/.metrics")
        assert status == 200
        assert ctype == pe.CONTENT_TYPE
        parsed = pe.parse_openmetrics(body.decode())
        job_id = app.status()["job"]
        _exact_cross_check(parsed, checker.metrics(), job_id)

        status, ctype, body = get(f"/.jobs/{job_id}/metrics.json?n=2")
        assert status == 200 and ctype == "application/json"
        doc = json.loads(body)
        assert doc["job"] == job_id and doc["window"] == 2
        assert len(doc["rows"]) >= 1
        assert {"v", "unix_ts", "t", "seq", "kind", "metrics"} == set(doc["rows"][-1])

        status, ctype, body = get("/.dash")
        assert status == 200 and ctype == "text/html"
        assert b"Pool dashboard" in body
        status, ctype, body = get("/dash.js")
        assert status == 200 and ctype == "text/javascript"
        assert b"/.jobs/" in body and b"/.pool" in body
    finally:
        server.shutdown()
        app.close()
        svc.close()


def test_batch_job_series_served_through_pool(tmp_path):
    """A real batch job records a per-job metrics.jsonl under its job dir
    (worker.py quiescent sampling + forced final row) and the pool serves
    it back windowed; /.metrics labels the finished job's recorded
    snapshot with its job id."""
    svc = _service(tmp_path)
    try:
        job = svc.submit("2pc:3")
        assert job.wait(timeout=300), "job did not finish"
        assert job.status == "done"
        assert (job.result["generated"], job.result["unique"]) == (1146, 288)
        rows = svc.job_metrics_series(job.id)
        assert rows, "no per-job series recorded"
        assert rows[-1]["metrics"]["state_count"] == 1146
        windowed = svc.job_metrics_series(job.id, window=1)
        assert len(windowed) == 1 and windowed[0] == rows[-1]
        with pytest.raises(KeyError):
            svc.job_metrics_series("nope")

        # The finished job's snapshot renders into /.metrics under its id.
        app, checker = make_app(MODEL.checker(), service=svc, **KW)
        try:
            parsed = pe.parse_openmetrics(app.metrics_text())
            m = job.metrics()
            labels = frozenset(
                {("job", job.id), ("engine", m["engine"]),
                 ("dedup", m["dedup"])}
            )
            assert parsed[("stpu_state_count_total", labels)] == 1146
            # And the HTTP-facing series handler finds it too.
            code, body = app.job_metrics(job.id, window=8)
            assert code == 200 and body["rows"][-1] == rows[-1]
        finally:
            app.close()
    finally:
        svc.close()
