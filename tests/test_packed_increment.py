"""Packed increment / increment_lock on the device engine vs the oracle.

Oracles: 13 unique states for the 2-thread racy increment, 8 under symmetry
reduction (examples/increment.rs:31-105); the lock variant satisfies both
``fin`` and ``mutex``. Counts come from full-space enumeration (a
``sometimes`` unreachable property forces exhaustion, as in
test_increment_examples.py).
"""

from stateright_tpu.core import Property
from stateright_tpu.models.increment import Increment, PackedIncrement
from stateright_tpu.models.increment_lock import IncrementLock, PackedIncrementLock

KW = dict(frontier_capacity=1 << 10, table_capacity=1 << 13)


class _FullSpace:
    """Mixin: replace the always-props with an unreachable sometimes so the
    search exhausts the space (engine early-exit otherwise stops at the
    race counterexample)."""

    def properties(self):
        return [Property.sometimes("unreachable", lambda _m, _s: False)]

    def packed_properties(self, words):
        import jax.numpy as jnp

        return jnp.stack([jnp.bool_(False)])


class _PackedIncrementFull(_FullSpace, PackedIncrement):
    pass


class _IncrementFull(_FullSpace, Increment):
    pass


class _PackedIncrementLockFull(_FullSpace, PackedIncrementLock):
    pass


class _IncrementLockFull(_FullSpace, IncrementLock):
    pass


def test_packed_increment_full_space_parity():
    assert _PackedIncrementFull(2).checker().spawn_xla(**KW).join().unique_state_count() == 13
    seq = _IncrementFull(3).checker().spawn_bfs().join()
    dev = _PackedIncrementFull(3).checker().spawn_xla(**KW).join()
    assert dev.unique_state_count() == seq.unique_state_count()
    assert dev.state_count() == seq.state_count()


def test_packed_increment_symmetry():
    dev = _PackedIncrementFull(2).checker().symmetry().spawn_xla(**KW).join()
    assert dev.unique_state_count() == 8


def test_packed_increment_race_discovery():
    dev = PackedIncrement(2).checker().spawn_xla(**KW).join()
    seq = Increment(2).checker().spawn_bfs().join()
    assert "fin" in dev.discoveries()
    # BFS witnesses are depth-minimal in both engines.
    assert len(dev.discoveries()["fin"]) == len(seq.discoveries()["fin"])
    final = dev.discoveries()["fin"].last_state()
    assert sum(1 for _t, pc in final.s if pc == 3) != final.i


def test_packed_increment_lock_full_space_parity():
    seq = _IncrementLockFull(2).checker().spawn_bfs().join()
    dev = _PackedIncrementLockFull(2).checker().spawn_xla(**KW).join()
    assert dev.unique_state_count() == seq.unique_state_count()
    assert dev.state_count() == seq.state_count()


def test_packed_increment_lock_holds():
    dev = PackedIncrementLock(2).checker().spawn_xla(**KW).join()
    dev.assert_properties()  # fin and mutex both hold
    assert dev.unique_state_count() > 0


def test_packed_increment_lock_symmetry_parity():
    seq = _IncrementLockFull(2).checker().symmetry().spawn_bfs().join()
    dev = _PackedIncrementLockFull(2).checker().symmetry().spawn_xla(**KW).join()
    assert dev.unique_state_count() == seq.unique_state_count()
