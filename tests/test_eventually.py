"""Eventually-property semantics, ported from
/root/reference/src/checker.rs:549-641 (including the documented
false-negative cases, which are part of the contract)."""

from stateright_tpu import Property
from stateright_tpu.test_util import DGraph


def eventually_odd() -> Property:
    return Property.eventually("odd", lambda _, s: s % 2 == 1)


def test_can_validate():
    (
        DGraph.with_property(eventually_odd())
        .with_path([1])  # satisfied at terminal init
        .with_path([2, 3])  # satisfied at nonterminal init
        .with_path([2, 6, 7])  # satisfied at terminal next
        .with_path([4, 9, 10])  # satisfied at nonterminal next
        .check()
        .assert_properties()
    )
    # Repeat with distinct state spaces since stateful checking skips visited
    # states (defense in depth).
    for path in ([1], [2, 3], [2, 6, 7], [4, 9, 10]):
        DGraph.with_property(eventually_odd()).with_path(path).check().assert_properties()


def test_can_discover_counterexample():
    c = DGraph.with_property(eventually_odd()).with_path([0, 1]).with_path([0, 2]).check()
    assert c.discovery("odd").into_states() == [0, 2]

    c = DGraph.with_property(eventually_odd()).with_path([0, 1]).with_path([2, 4]).check()
    assert c.discovery("odd").into_states() == [2, 4]

    c = (
        DGraph.with_property(eventually_odd())
        .with_path([0, 1, 4, 6])
        .with_path([2, 4, 8])
        .check()
    )
    assert c.discovery("odd").into_states() == [2, 4, 6]


def test_fixme_can_miss_counterexample_when_revisiting_a_state():
    # Cycles are not treated as terminal states, so an eventually-property
    # counterexample through a cycle is missed — a false negative the
    # reference documents (checker.rs:623-640) and we replicate.
    c = DGraph.with_property(eventually_odd()).with_path([0, 2, 4, 2]).check()
    assert c.discovery("odd") is None

    c = (
        DGraph.with_property(eventually_odd())
        .with_path([0, 2, 4])
        .with_path([1, 4, 6])  # revisiting 4
        .check()
    )
    assert c.discovery("odd") is None
