"""Bucket-ladder policy: "jump" (growth-extrapolated rung skipping) vs
"ramp" (one power-of-four rung per overflow).

Each distinct run bucket is a separate XLA compilation of the full
superstep program, and compile cost is dominated by program complexity,
not bucket size (round-5 measurement: paxos 2c/3s ~11 s/bucket on 1-core
CPU at every bucket from 64 to 4096) — so skipped rungs are pure
time-to-first-result savings. Counts are bucket-independent: both
policies must land the pinned exact counts.
"""

import pytest

from stateright_tpu.models.two_phase_commit import PackedTwoPhaseSys


def _run(ladder, model, **kw):
    checker = model.checker().spawn_xla(ladder=ladder, **kw)
    while not checker.is_done():
        checker._run_block()
    return checker


KW = dict(frontier_capacity=1 << 12, table_capacity=1 << 14)


def test_jump_compiles_fewer_buckets_same_counts():
    ramp = _run("ramp", PackedTwoPhaseSys(4), **KW)
    jump = _run("jump", PackedTwoPhaseSys(4), **KW)
    pinned = (8_258, 1_568)
    assert (ramp.state_count(), ramp.unique_state_count()) == pinned
    assert (jump.state_count(), jump.unique_state_count()) == pinned
    ramp_buckets = ramp._compiled_run_caps()
    jump_buckets = jump._compiled_run_caps()
    assert len(jump_buckets) < len(ramp_buckets), (jump_buckets, ramp_buckets)


def test_second_pass_compiles_nothing_new():
    """The measured pass must ride the warm pass's compilations: same
    model, same policy => the bucket set cannot grow on pass 2."""
    model = PackedTwoPhaseSys(4)
    warm = _run("jump", model, **KW)
    warm_buckets = set(warm._compiled_run_caps())
    measured = _run("jump", model, **KW)
    assert set(measured._compiled_run_caps()) == warm_buckets
    assert (measured.state_count(), measured.unique_state_count()) == (8_258, 1_568)


def test_deep_narrow_space_stays_on_the_floor_bucket():
    """A space that never widens past the 64-row floor must not jump:
    the floor-64 win for consistency-tester shapes (round 4) is invariant
    under the ladder policy."""
    from stateright_tpu.models.increment_lock import PackedIncrementLock

    for ladder in ("ramp", "jump"):
        checker = _run(
            ladder,
            PackedIncrementLock(3),
            frontier_capacity=1 << 10,
            table_capacity=1 << 13,
        )
        assert checker._compiled_run_caps() == {64}
        assert checker.state_count() == 61


def test_ladder_validation():
    with pytest.raises(ValueError, match="ladder"):
        PackedTwoPhaseSys(3).checker().spawn_xla(ladder="sideways", **KW)
