"""Bucket-ladder policy: "jump" (growth-extrapolated rung skipping) vs
"ramp" (one power-of-four rung per overflow).

Each distinct run bucket is a separate XLA compilation of the full
superstep program, and compile cost is dominated by program complexity,
not bucket size (round-5 measurement: paxos 2c/3s ~11 s/bucket on 1-core
CPU at every bucket from 64 to 4096) — so skipped rungs are pure
time-to-first-result savings. Counts are bucket-independent: both
policies must land the pinned exact counts.
"""

import pytest

from stateright_tpu.core import Model
from stateright_tpu.models.two_phase_commit import PackedTwoPhaseSys


def _run(ladder, model, **kw):
    checker = model.checker().spawn_xla(ladder=ladder, **kw)
    while not checker.is_done():
        checker._run_block()
    return checker


KW = dict(frontier_capacity=1 << 12, table_capacity=1 << 14)


def test_jump_compiles_fewer_buckets_same_counts():
    ramp = _run("ramp", PackedTwoPhaseSys(4), **KW)
    jump = _run("jump", PackedTwoPhaseSys(4), **KW)
    pinned = (8_258, 1_568)
    assert (ramp.state_count(), ramp.unique_state_count()) == pinned
    assert (jump.state_count(), jump.unique_state_count()) == pinned
    ramp_buckets = ramp._compiled_run_caps()
    jump_buckets = jump._compiled_run_caps()
    assert len(jump_buckets) < len(ramp_buckets), (jump_buckets, ramp_buckets)


def test_second_pass_compiles_nothing_new():
    """The measured pass must ride the warm pass's compilations: same
    model, same policy => the bucket set cannot grow on pass 2."""
    model = PackedTwoPhaseSys(4)
    warm = _run("jump", model, **KW)
    warm_buckets = set(warm._compiled_run_caps())
    measured = _run("jump", model, **KW)
    assert set(measured._compiled_run_caps()) == warm_buckets
    assert (measured.state_count(), measured.unique_state_count()) == (8_258, 1_568)


def test_deep_narrow_space_stays_on_the_floor_bucket():
    """A space that never widens past the 64-row floor must not jump:
    the floor-64 win for consistency-tester shapes (round 4) is invariant
    under the ladder policy."""
    from stateright_tpu.models.increment_lock import PackedIncrementLock

    for ladder in ("ramp", "jump"):
        checker = _run(
            ladder,
            PackedIncrementLock(3),
            frontier_capacity=1 << 10,
            table_capacity=1 << 13,
        )
        assert checker._compiled_run_caps() == {64}
        assert checker.state_count() == 61


def test_ladder_validation():
    with pytest.raises(ValueError, match="ladder"):
        PackedTwoPhaseSys(3).checker().spawn_xla(ladder="sideways", **KW)


def assert_tail_downshift(dispatch_log):
    """At least one dispatch after the peak bucket ran below it (the
    shrink-exit fired). Shared by the delta-interplay test."""
    caps = [cap for cap, _ in dispatch_log]
    peak = max(caps)
    after_peak = caps[caps.index(peak) + 1 :]
    assert after_peak and min(after_peak) < peak, dispatch_log


def test_tail_shrink_exit_redispatches_snug():
    """Once the frontier collapses past the peak, the fused loop must hand
    the tail levels back to smaller already-compiled buckets (the
    shrink-exit) instead of paying the peak bucket's grid sort per level —
    and the downshift must never compile a new bucket or change counts."""
    for ladder in ("ramp", "jump"):
        model = PackedTwoPhaseSys(4)
        checker = model.checker().spawn_xla(ladder=ladder, **KW)
        # Spy on program-cache misses per dispatch: a fresh cache key
        # appearing in a dispatch AFTER the peak bucket's first dispatch
        # would mean the downshift compiled a new bucket.
        orig = checker._fused_for
        miss_log = []

        def spying_fused_for(f_cap):
            before = set(checker._superstep_cache)
            fn = orig(f_cap)
            miss_log.append((f_cap, bool(set(checker._superstep_cache) - before)))
            return fn

        checker._fused_for = spying_fused_for
        while not checker.is_done():
            checker._run_block()
        assert (checker.state_count(), checker.unique_state_count()) == (
            8_258,
            1_568,
        ), ladder
        # The 2pc tail collapses to single digits: at least one tail
        # dispatch must run below the peak bucket...
        assert_tail_downshift(checker.dispatch_log)
        # ...with every post-peak dispatch a pure cache hit.
        caps = [cap for cap, _ in checker.dispatch_log]
        peak = max(caps)
        past_peak = False
        for f_cap, missed in miss_log:
            if f_cap == peak:
                past_peak = True
            elif past_peak:
                assert not missed, (ladder, miss_log)


class _StarModel(Model):
    """Synthetic PackedModel: one root fanning out to ``fan`` leaves in a
    single level. With fan > 64 the depth-1 level overflows the 64-row
    floor bucket while the stored frontier is a single row — the shape
    whose post-grow shrink threshold (64 // 4 = 16) exceeds the frontier."""

    def __init__(self, fan=80):
        self.fan = fan
        self.state_words = 1
        self.max_actions = fan

    def init_states(self):
        return [0]

    def actions(self, state, actions):
        if state == 0:
            actions.extend(range(self.fan))

    def next_state(self, state, action):
        return action + 1

    def pack(self, state):
        import numpy as np

        return np.asarray([state], np.uint32)

    def unpack(self, words):
        return int(words[0])

    def packed_init(self):
        import numpy as np

        return np.zeros((1, 1), np.uint32)

    def packed_step(self, words):
        import jax.numpy as jnp

        at_root = words[0] == 0
        nxt = jnp.arange(1, self.fan + 1, dtype=jnp.uint32)[:, None]
        valid = jnp.broadcast_to(at_root, (self.fan,))
        return nxt, valid

    def packed_properties(self, words):
        import jax.numpy as jnp

        return jnp.zeros((0,), jnp.bool_)


def test_overflow_grow_never_stalls_at_level_zero():
    """A frontier overflow can leave the stored frontier at or below the
    grown dispatch's shrink threshold (star root: 1 row overflows the
    64-row floor with 300 uniques; two grow rounds land at bucket 1024,
    whose threshold 256 // 4 = 64 >= 1 — fan must exceed 256 because
    buckets <= 256 never set a shrink threshold). The fused loop's
    committed==0 bypass must keep such an entry committing its first
    level; without it the checker livelocks (level-0 stall -> break ->
    identical re-entry, forever)."""
    checker = _StarModel(300).checker().spawn_xla(
        frontier_capacity=1 << 10, table_capacity=1 << 12
    )
    for _ in range(20):
        if checker.is_done():
            break
        checker._run_block()
    assert checker.is_done(), checker.dispatch_log
    assert checker.unique_state_count() == 301
    assert checker.state_count() == 301  # init + 300 generated leaves
    # Dequeue-time depth bookkeeping (bfs.rs:257-272): the terminal
    # leaves' frontier is counted at depth 2 before being found empty.
    assert checker.max_depth() == 2


def test_shrink_exit_off_never_downshifts():
    """``shrink_exit='off'`` (the accelerator auto: each tail downshift
    is a host round-trip, and over the TPU tunnel the rm=8 A/B measured
    the re-dispatch RTT above the snug-sort savings) must keep the
    dispatch caps nondecreasing with counts unchanged."""
    model = PackedTwoPhaseSys(4)
    checker = model.checker().spawn_xla(
        ladder="ramp", shrink_exit="off", **KW
    )
    while not checker.is_done():
        checker._run_block()
    assert (checker.state_count(), checker.unique_state_count()) == (8_258, 1_568)
    caps = [cap for cap, _ in checker.dispatch_log]
    assert caps == sorted(caps), checker.dispatch_log


def test_shrink_exit_validation():
    with pytest.raises(ValueError, match="shrink_exit"):
        PackedTwoPhaseSys(3).checker().spawn_xla(shrink_exit="maybe", **KW)
