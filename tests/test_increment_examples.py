"""Parity tests for the increment / increment_lock / timers examples.

Oracles: the reference's doc comment enumerates the racy-increment state
space for 2 threads — 13 unique states plain, 8 under symmetry reduction
(examples/increment.rs:31-105). The lock variant satisfies both ``fin`` and
``mutex`` (examples/increment_lock.rs:97-106). The timers example exercises
timer re-arm no-op suppression (examples/timers.rs:91-94).
"""

from stateright_tpu.models.increment import Increment, IncrementState
from stateright_tpu.models.increment_lock import IncrementLock
from stateright_tpu.models.timers import timers_model


class _IncrementFullSpace(Increment):
    """Full-space enumeration: with the lone ``always`` property the checker
    stops at its first counterexample, and with no properties it is done
    immediately (0 discoveries == 0 properties — both per the reference,
    bfs.rs:160-171), so the doc-comment counts of 13/8
    (increment.rs:31-105) are only observable with an unreachable
    ``sometimes`` property forcing exhaustion."""

    def properties(self):
        from stateright_tpu.core import Property

        return [Property.sometimes("unreachable", lambda _m, _s: False)]


def test_increment_two_threads_finds_race():
    checker = Increment(2).checker().spawn_bfs().join()
    cex = checker.discoveries()["fin"]
    # The shortest violation: both threads read 0, then both write 1
    # (increment.rs:63-71).
    final = cex.into_vec()[-1][0]
    assert final.i < sum(1 for _t, pc in final.s if pc == 3)


def test_increment_full_space_is_13_states():
    checker = _IncrementFullSpace(2).checker().spawn_bfs().join()
    assert checker.unique_state_count() == 13


def test_increment_symmetry_reduces_13_to_8():
    checker = _IncrementFullSpace(2).checker().symmetry().spawn_dfs().join()
    assert checker.unique_state_count() == 8


def test_increment_symmetry_still_finds_race():
    checker = Increment(2).checker().symmetry().spawn_dfs().join()
    assert "fin" in checker.discoveries()


def test_increment_representative_sorts_threads():
    s = IncrementState(1, ((1, 3), (0, 1)))
    assert s.representative() == IncrementState(1, ((0, 1), (1, 3)))


def test_increment_lock_holds_invariants():
    checker = IncrementLock(2).checker().spawn_bfs().join()
    checker.assert_no_discovery("fin")
    checker.assert_no_discovery("mutex")
    # 2 threads * 5 pc positions serialized by the lock: a small space.
    assert checker.unique_state_count() > 0


def test_increment_lock_symmetry_agrees_on_properties():
    plain = IncrementLock(3).checker().spawn_dfs().join()
    sym = IncrementLock(3).checker().symmetry().spawn_dfs().join()
    plain.assert_properties()
    sym.assert_properties()
    assert sym.unique_state_count() <= plain.unique_state_count()


def test_timers_bounded_check():
    checker = (
        timers_model(server_count=2)
        .checker()
        .target_state_count(2_000)
        .spawn_bfs()
        .join()
    )
    # target_state_count bounds total generated states (checker.rs:215-222);
    # the run must not stop short of it while more states exist.
    assert checker.state_count() >= 2_000
    assert checker.unique_state_count() > 0
    # "true" always holds, so no discovery.
    checker.assert_no_discovery("true")


def test_timers_noop_rearm_is_suppressed():
    # A NoOp timeout re-arms the same timer and does nothing else; the model
    # must suppress it (is_no_op_with_timer, actor.rs:254-264), or the state
    # graph would contain a self-loop at every state. Even/Odd timeouts DO
    # send pings, so they must survive suppression.
    from stateright_tpu.models.timers import NoOp

    model = timers_model(server_count=2)
    init = model.init_states()[0]
    steps = model.next_steps(init)
    assert steps, "Even/Odd timeouts must produce steps"
    for action, state in steps:
        assert not isinstance(action.timer, NoOp)
        assert state != init
