"""Parity tests for the register example models.

Oracles are the reference's own tests:

- single-copy register: linearizable iff one server; 93 unique states at
  2 clients / 1 server (DFS, full coverage) and 20 at 2 clients / 2 servers
  (BFS, stops at the linearizability counterexample)
  (examples/single-copy-register.rs:88-137).
- ABD linearizable register: always linearizable; 544 unique states at
  2 clients / 2 servers, both BFS and DFS
  (examples/linearizable-register.rs:259-317).
"""

from stateright_tpu.actor import register as reg
from stateright_tpu.actor.model import DeliverAction
from stateright_tpu.models.linearizable_register import linearizable_register_model
from stateright_tpu.models.single_copy_register import single_copy_register_model


def test_single_copy_one_server_is_linearizable():
    checker = (
        single_copy_register_model(client_count=2, server_count=1)
        .checker()
        .spawn_dfs()
        .join()
    )
    checker.assert_properties()
    assert checker.unique_state_count() == 93
    witness = checker.discoveries()["value chosen"]
    actions = [a for _s, a in witness.into_vec() if a is not None]
    assert all(isinstance(a, DeliverAction) for a in actions)


def test_single_copy_two_servers_not_linearizable():
    checker = (
        single_copy_register_model(client_count=2, server_count=2)
        .checker()
        .spawn_bfs()
        .join()
    )
    assert checker.unique_state_count() == 20
    cex = checker.discoveries()["linearizable"]
    actions = [a for _s, a in cex.into_vec() if a is not None]
    # The shortest counterexample: Put to one server acked, then a Get served
    # stale by the other server (single-copy-register.rs:123-128).
    assert len(actions) == 4
    assert isinstance(actions[0].msg, reg.Put)
    assert isinstance(actions[-1].msg, reg.GetOk)
    assert actions[-1].msg.value is None
    assert "value chosen" in checker.discoveries()


def _check_abd(spawn, shortest_witness):
    checker = (
        spawn(linearizable_register_model(client_count=2, server_count=2).checker())
        .join()
    )
    checker.assert_properties()
    assert checker.unique_state_count() == 544
    witness = checker.discoveries()["value chosen"]
    actions = [a for _s, a in witness.into_vec() if a is not None]
    if shortest_witness:
        # Put (2 phases against a quorum) then Get reaching its quorum
        # (linearizable-register.rs:276-288): 11 deliveries.
        assert len(actions) == 11
        assert isinstance(actions[0].msg, reg.Put)
    assert all(isinstance(a, DeliverAction) for a in actions)


def test_can_model_linearizable_register_bfs():
    _check_abd(lambda b: b.spawn_bfs(), shortest_witness=True)


def test_can_model_linearizable_register_dfs():
    _check_abd(lambda b: b.spawn_dfs(), shortest_witness=False)
