"""Fused multi-level dispatch vs one-level-per-dispatch equivalence.

The engines' fused blocks (xla.py ``_build_fused``, sharded.py
``_build_fused``) claim level-granularity semantic equivalence with the
single-level path: identical counts, depths, and discoveries, including on
early-exit runs (all properties found) and capped runs (state-count and
depth targets). These tests pin that claim on both engines.
"""

import jax
import pytest

from stateright_tpu.core import Property
from stateright_tpu.models.paxos import PackedPaxos
from stateright_tpu.models.two_phase_commit import PackedTwoPhaseSys
from stateright_tpu.test_util import DGraph, PackedDGraph


def _spawn(model, levels, **kw):
    return model.checker().spawn_xla(levels_per_dispatch=levels, **kw)


def _summary(c):
    return (
        c.state_count(),
        c.unique_state_count(),
        c.max_depth(),
        {n: p.into_actions() for n, p in c.discoveries().items()},
    )


KW = dict(frontier_capacity=1 << 10, table_capacity=1 << 13)


def test_fused_matches_single_full_coverage():
    a = _spawn(PackedTwoPhaseSys(3), 1, **KW).join()
    b = _spawn(PackedTwoPhaseSys(3), 32, **KW).join()
    assert _summary(a) == _summary(b)
    assert b.unique_state_count() == 288


SEMANTIC_KEYS = ("depth", "frontier", "generated", "unique")


def _semantic(log):
    """The engine-independent telemetry projection: dispatch-SHAPE keys
    (bucket / cand_cap / lane_words) legitimately differ between dispatch
    granularities — the one-level path picks its bucket per level on the
    host while a fused block runs one bucket (and, with the candidate
    ladder, per-level in-program sub-widths)."""
    return [{k: r[k] for k in SEMANTIC_KEYS} for r in log]


def test_fused_level_log_matches_single():
    # Per-level telemetry must survive fused dispatch: identical
    # {depth, frontier, generated, unique} rows to the one-level path, and
    # rows must reconcile with the totals (inits are counted in totals but
    # predate level 1).
    a = _spawn(PackedTwoPhaseSys(3), 1, **KW).join()
    b = _spawn(PackedTwoPhaseSys(3), 32, **KW).join()
    assert _semantic(b.level_log) == _semantic(a.level_log)
    # Every row carries the dispatch-shape telemetry on both paths.
    for row in a.level_log + b.level_log:
        assert {"bucket", "cand_cap", "lane_words"} <= set(row)
    # One row per expanded level, depths 1..max_depth (the last expansion
    # finds nothing new but is itself a row).
    assert [r["depth"] for r in b.level_log] == list(range(1, b.max_depth() + 1))
    n_init = 1
    assert sum(r["generated"] for r in b.level_log) + n_init == b.state_count()
    assert sum(r["unique"] for r in b.level_log) + n_init == b.unique_state_count()


def test_fused_matches_single_early_exit():
    # An eventually-property counterexample (terminal even node) plus a
    # long tail: exercises the on-device terminal detection and the
    # early-exit-at-level-granularity claim.
    g = PackedDGraph(
        DGraph.with_property(
            Property.eventually("odd", lambda _, s: s % 2 == 1)
        )
        .with_path([0, 2, 4])
        .with_path([0, 6, 8, 10, 12])
    )
    a = _spawn(g, 1, **KW).join()
    b = _spawn(g, 32, **KW).join()
    assert _summary(a) == _summary(b)


def test_fused_matches_single_targets():
    for target_kind in ("count", "depth"):
        ma, mb = PackedTwoPhaseSys(3), PackedTwoPhaseSys(3)
        ba, bb = ma.checker(), mb.checker()
        if target_kind == "count":
            ba.target_state_count(100)
            bb.target_state_count(100)
        else:
            ba.target_max_depth(3)
            bb.target_max_depth(3)
        a = ba.spawn_xla(levels_per_dispatch=1, **KW).join()
        b = bb.spawn_xla(levels_per_dispatch=32, **KW).join()
        assert (a.state_count(), a.unique_state_count(), a.max_depth()) == (
            b.state_count(),
            b.unique_state_count(),
            b.max_depth(),
        ), target_kind


def test_fused_matches_single_hv_properties():
    # Paxos-sized hv runs are slow; DGraph-based hv coverage lives in
    # test_host_verified.py. Here: the paxos model itself (exact device
    # linearizability, always+sometimes mix) at a small budget boundary —
    # levels_per_dispatch=2 forces several block re-entries.
    kw = dict(frontier_capacity=1 << 12, table_capacity=1 << 16)
    a = _spawn(PackedPaxos(2, 3), 2, **kw).join()
    b = _spawn(PackedPaxos(2, 3), 64, **kw).join()
    assert _summary(a) == _summary(b)
    assert b.unique_state_count() == 16668


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs the 8-device mesh")
def test_fused_matches_single_sharded():
    from stateright_tpu.parallel import default_mesh

    kw = dict(mesh=default_mesh(8), frontier_capacity=1 << 10, table_capacity=1 << 13)
    a = _spawn(PackedTwoPhaseSys(3), 1, **kw).join()
    b = _spawn(PackedTwoPhaseSys(3), 32, **kw).join()
    assert _summary(a) == _summary(b)
    assert b.unique_state_count() == 288


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs the 8-device mesh")
def test_fused_matches_single_sharded_targets():
    from stateright_tpu.parallel import default_mesh

    mesh = default_mesh(8)
    for target_kind in ("count", "depth"):
        ba = PackedTwoPhaseSys(3).checker()
        bb = PackedTwoPhaseSys(3).checker()
        if target_kind == "count":
            ba.target_state_count(100)
            bb.target_state_count(100)
        else:
            ba.target_max_depth(3)
            bb.target_max_depth(3)
        kw = dict(mesh=mesh, frontier_capacity=1 << 10, table_capacity=1 << 13)
        a = ba.spawn_xla(levels_per_dispatch=1, **kw).join()
        b = bb.spawn_xla(levels_per_dispatch=32, **kw).join()
        assert (a.state_count(), a.unique_state_count(), a.max_depth()) == (
            b.state_count(),
            b.unique_state_count(),
            b.max_depth(),
        ), target_kind
