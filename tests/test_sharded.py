"""Sharded (multi-chip) XLA engine tests on the virtual 8-device CPU mesh.

The fingerprint-sharded engine (stateright_tpu/parallel/sharded.py) must
reproduce the CPU oracle's counts and witness semantics exactly — same
differential strategy as the single-chip XLA tests, plus routing/growth
paths that only exist in the distributed engine.
"""

import numpy as np
import pytest

import jax

from stateright_tpu.core import Property
from stateright_tpu.models.two_phase_commit import PackedTwoPhaseSys, TwoPhaseSys
from stateright_tpu.parallel import ShardedXlaChecker, default_mesh
from stateright_tpu.test_util import DGraph, PackedDGraph

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the 8-device virtual CPU mesh"
)


def _mesh(n=8):
    return default_mesh(n)


def test_spawn_xla_dispatches_to_sharded_engine():
    checker = PackedTwoPhaseSys(3).checker().spawn_xla(
        mesh=_mesh(), frontier_capacity=1 << 10, table_capacity=1 << 13
    )
    assert isinstance(checker, ShardedXlaChecker)


def test_sharded_2pc_rm3_matches_oracle():
    cpu = TwoPhaseSys(3).checker().spawn_bfs().join()
    xla = (
        PackedTwoPhaseSys(3)
        .checker()
        .spawn_xla(mesh=_mesh(), frontier_capacity=1 << 10, table_capacity=1 << 13)
        .join()
    )
    assert xla.unique_state_count() == cpu.unique_state_count() == 288
    assert xla.state_count() == cpu.state_count()
    assert xla.max_depth() == cpu.max_depth()
    assert set(xla.discoveries()) == set(cpu.discoveries())
    xla.assert_properties()


def test_sharded_discovery_paths_are_valid():
    xla = (
        PackedTwoPhaseSys(3)
        .checker()
        .spawn_xla(mesh=_mesh(), frontier_capacity=1 << 10, table_capacity=1 << 13)
        .join()
    )
    model = TwoPhaseSys(3)
    for name, path in xla.discoveries().items():
        # Replaying the witness's actions from init must reach a state
        # satisfying the property (the assert_discovery contract).
        prop = model.property(name)
        assert prop.condition(model, path.last_state())


def test_sharded_capacity_autogrowth():
    # Tiny per-shard capacities: 2pc(rm=4) has 1,568 unique states
    # (~196/shard), so a 64-slot/shard table MUST overflow and grow, the
    # 16-row/shard frontier must grow, and an 8-slot routing buffer must
    # grow — rather than fail.
    checker = (
        PackedTwoPhaseSys(4)
        .checker()
        .spawn_xla(
            mesh=_mesh(),
            frontier_capacity=1 << 7,  # 16 rows/shard
            table_capacity=1 << 9,  # 64 slots/shard
            route_capacity=8,
        )
        .join()
    )
    assert checker.unique_state_count() == 1_568
    assert checker._Cl > 64, "table growth must actually have fired"
    checker.assert_properties()


def test_single_device_mesh_falls_back_to_single_chip_engine():
    from stateright_tpu.xla import XlaChecker

    checker = PackedTwoPhaseSys(3).checker().spawn_xla(
        mesh=_mesh(1), route_capacity=8,
        frontier_capacity=1 << 10, table_capacity=1 << 13,
    )
    assert isinstance(checker, XlaChecker)


def test_sharded_4_device_mesh():
    checker = (
        PackedTwoPhaseSys(3)
        .checker()
        .spawn_xla(mesh=_mesh(4), frontier_capacity=1 << 10, table_capacity=1 << 13)
        .join()
    )
    assert checker.unique_state_count() == 288


@pytest.mark.slow
def test_sharded_2pc_rm5_matches_oracle():
    checker = (
        PackedTwoPhaseSys(5)
        .checker()
        .spawn_xla(mesh=_mesh(), frontier_capacity=1 << 12, table_capacity=1 << 16)
        .join()
    )
    assert checker.unique_state_count() == 8_832
    checker.assert_properties()


def test_sharded_eventually_semantics():
    def eventually_odd():
        return Property.eventually("odd", lambda _, s: s % 2 == 1)

    def check(graph):
        return (
            PackedDGraph(graph)
            .checker()
            .spawn_xla(mesh=_mesh(), frontier_capacity=1 << 8, table_capacity=1 << 11)
            .join()
        )

    c = check(DGraph.with_property(eventually_odd()).with_path([0, 1]).with_path([0, 2]))
    assert c.discovery("odd").into_states() == [0, 2]

    # The documented cycle false negative transfers (checker.rs:623-640).
    c = check(DGraph.with_property(eventually_odd()).with_path([0, 2, 4, 2]))
    assert c.discovery("odd") is None


def test_sharded_target_state_count():
    checker = (
        PackedTwoPhaseSys(4)
        .checker()
        .target_state_count(100)
        .spawn_xla(mesh=_mesh(), frontier_capacity=1 << 10, table_capacity=1 << 13)
        .join()
    )
    assert checker.is_done()
    assert checker.state_count() >= 100


def test_sharded_symmetry_reduction_matches_perfect_canonicalizer():
    """Symmetry on the mesh engine: the racy increment's representative
    (sorted thread tuples) is a PERFECT canonicalizer, so the reduced
    count is exploration-order-invariant — host, single-chip, and sharded
    engines must all see exactly 8 classes for 2 threads
    (increment.rs:31-105)."""
    from stateright_tpu.core import Property
    from stateright_tpu.models.increment import Increment, PackedIncrement

    class _Full(PackedIncrement):
        def properties(self):
            return [Property.sometimes("unreachable", lambda _m, _s: False)]

        def packed_properties(self, words):
            import jax.numpy as jnp

            return jnp.stack([jnp.bool_(False)])

    kw = dict(frontier_capacity=1 << 10, table_capacity=1 << 13)
    single = _Full(2).checker().symmetry().spawn_xla(**kw).join()
    sharded = _Full(2).checker().symmetry().spawn_xla(mesh=_mesh(), **kw).join()
    assert single.unique_state_count() == 8
    assert sharded.unique_state_count() == 8
    assert sharded.state_count() == single.state_count()


def test_sharded_sorted_dedup_matches_hash_engine():
    """The sharded sorted/planes path (per-shard sort-merge set, gather
    routing pack, gather frontier compaction) is lane-for-lane equivalent
    to the hash/scatter path: counts, depth, AND witness paths agree."""
    kw = dict(mesh=_mesh(), frontier_capacity=1 << 10, table_capacity=1 << 13)
    a = PackedTwoPhaseSys(3).checker().spawn_xla(dedup="hash", **kw).join()
    b = PackedTwoPhaseSys(3).checker().spawn_xla(dedup="sorted", **kw).join()
    assert (a.state_count(), a.unique_state_count(), a.max_depth()) == (
        b.state_count(),
        b.unique_state_count(),
        b.max_depth(),
    )
    da, db = a.discoveries(), b.discoveries()
    assert set(da) == set(db) and da
    for name in da:
        assert da[name].into_states() == db[name].into_states()


def test_sharded_sorted_matches_single_chip_sorted():
    """Mesh-vs-single-chip parity under the sorted structure (the TPU
    default on both engines)."""
    b = (
        PackedTwoPhaseSys(3)
        .checker()
        .spawn_xla(
            mesh=_mesh(), dedup="sorted",
            frontier_capacity=1 << 10, table_capacity=1 << 13,
        )
        .join()
    )
    c = (
        PackedTwoPhaseSys(3)
        .checker()
        .spawn_xla(
            dedup="sorted", frontier_capacity=1 << 10, table_capacity=1 << 12
        )
        .join()
    )
    assert b.unique_state_count() == c.unique_state_count() == 288
    assert b.state_count() == c.state_count()
    assert b.max_depth() == c.max_depth()


def test_sharded_sorted_capacity_autogrowth():
    """Table/route/frontier growth under the sorted structure: plane-copy
    growth (no rehash) must preserve the per-shard sorted invariant."""
    c = (
        PackedTwoPhaseSys(4)
        .checker()
        .spawn_xla(
            mesh=_mesh(),
            dedup="sorted",
            frontier_capacity=1 << 7,
            table_capacity=1 << 9,
            route_capacity=4,
        )
        .join()
    )
    assert c.unique_state_count() == 1_568  # 2pc rm=4 (same anchor as above)
    kh = np.asarray(c._table.key_hi).reshape(8, -1)
    kl = np.asarray(c._table.key_lo).reshape(8, -1)
    ns = np.asarray(c._table.n)
    for d in range(8):
        n = int(ns[d])
        keys = (kh[d, :n].astype(np.uint64) << 32) | kl[d, :n]
        assert np.all(keys[1:] > keys[:-1]), f"shard {d} prefix not sorted"
        assert not np.any(kh[d, n:]) and not np.any(kl[d, n:])


def test_sharded_delta_dedup_matches_sorted():
    """Per-shard two-tier delta tables (dedup="delta") on the mesh:
    counts, witness paths, and the in-kernel flush all must reproduce the
    sorted engine exactly (tiny tiers force flushes and a growth)."""
    kw = dict(mesh=_mesh(), frontier_capacity=1 << 10)
    a = (
        PackedTwoPhaseSys(4)
        .checker()
        .spawn_xla(dedup="sorted", table_capacity=1 << 13, **kw)
        .join()
    )
    b = (
        PackedTwoPhaseSys(4)
        .checker()
        .spawn_xla(dedup="delta", table_capacity=1 << 10, **kw)
        .join()
    )
    assert (a.state_count(), a.unique_state_count(), a.max_depth()) == (
        b.state_count(),
        b.unique_state_count(),
        b.max_depth(),
    )
    assert b.unique_state_count() == 1_568
    da, db = a.discoveries(), b.discoveries()
    assert set(da) == set(db) and da
    for name in da:
        assert da[name].into_states() == db[name].into_states()


# --- host-verified properties on the mesh (VERDICT r3 #4) -----------------


def _hv_scr(*args):
    """A single-copy register routed through the engine's host-verified
    path (the public ``device_exact=False`` switch): isolates the mesh's
    candidate compaction / allgather / host-confirm machinery at
    test-suite scale — sound because the sampled predicate's limit far
    exceeds these shapes' full enumerations, so it stays exact."""
    from stateright_tpu.models.single_copy_register import (
        PackedSingleCopyRegister,
    )

    return PackedSingleCopyRegister(*args, device_exact=False)


def test_sharded_hv_counterexample_single_copy_2c2s():
    from stateright_tpu.models.single_copy_register import (
        PackedSingleCopyRegister,
    )

    # The stale-read counterexample config (single-copy-register.rs:136).
    # Parity target is the single-chip DEVICE engine with hv forced the
    # same way: both engines stop at the end of the level where the host
    # confirms the violation.
    single = (
        _hv_scr(2, 2)
        .checker()
        .spawn_xla(frontier_capacity=1 << 9, table_capacity=1 << 11)
        .join()
    )
    mesh = (
        _hv_scr(2, 2)
        .checker()
        .spawn_xla(
            mesh=_mesh(), frontier_capacity=1 << 9, table_capacity=1 << 11
        )
        .join()
    )
    assert "linearizable" in mesh.discoveries()
    assert set(mesh.discoveries()) == set(single.discoveries())
    assert mesh.unique_state_count() == single.unique_state_count()
    assert mesh.state_count() == single.state_count()
    # The witness must be a real path ending in a non-linearizable state.
    mesh.assert_discovery(
        "linearizable", mesh.discoveries()["linearizable"].into_actions()
    )


def test_sharded_hv_full_coverage_single_copy_2c1s():
    from stateright_tpu.models.single_copy_register import (
        PackedSingleCopyRegister,
    )

    # One server: 'linearizable' HOLDS, so the hv path must confirm nothing
    # and the search must reach exact full coverage (the 93-state anchor,
    # single-copy-register.rs:110).
    mesh = (
        _hv_scr(2, 1)
        .checker()
        .spawn_xla(
            mesh=_mesh(), frontier_capacity=1 << 9, table_capacity=1 << 11
        )
        .join()
    )
    assert mesh.unique_state_count() == 93
    assert mesh.state_count() == 121
    assert "linearizable" not in mesh.discoveries()
    mesh.assert_properties()


def test_sharded_device_exact_lin_models_mesh_parity():
    from stateright_tpu.models.linearizable_register import PackedAbd
    from stateright_tpu.models.single_copy_register import (
        PackedSingleCopyRegister,
    )

    # ABD 2c/2s reaches full coverage: the 544-state reference anchor
    # (linearizable-register.rs:289) must hold exactly on the mesh.
    abd = (
        PackedAbd(2, 2)
        .checker()
        .spawn_xla(
            mesh=_mesh(), frontier_capacity=1 << 10, table_capacity=1 << 12
        )
        .join()
    )
    assert abd.unique_state_count() == 544
    assert abd.state_count() == 875
    assert set(abd.discoveries()) == {"value chosen"}

    # single-copy 2c/2s stops at the counterexample; parity target is the
    # single-chip device engine (same level-synchronous early exit).
    single = (
        PackedSingleCopyRegister(2, 2)
        .checker()
        .spawn_xla(frontier_capacity=1 << 9, table_capacity=1 << 11)
        .join()
    )
    mesh = (
        PackedSingleCopyRegister(2, 2)
        .checker()
        .spawn_xla(
            mesh=_mesh(), frontier_capacity=1 << 9, table_capacity=1 << 11
        )
        .join()
    )
    assert set(mesh.discoveries()) == set(single.discoveries())
    assert mesh.unique_state_count() == single.unique_state_count()
    assert mesh.state_count() == single.state_count()
