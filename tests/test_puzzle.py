"""Sliding puzzle — the reference's first-model doc example (lib.rs:40-115).

The doc-test assertions from the reference are pinned here: the doc board
``[1,4,2,3,5,8,6,7,0]`` has a solution, discovered and validated via
``assert_discovery`` with the exact 4-slide path (lib.rs:97-115). The
packed form is parity-checked against the host oracle at full coverage on
an unsolvable 2x2 board (the ``sometimes`` property never fires, so both
engines sweep the whole 12-state component instead of early-stopping at
the discovery, which they do at different granularity: the host oracle
mid-level, the device engine level-synchronously).
"""

import pytest

from stateright_tpu.models.puzzle import PackedPuzzle, Puzzle

DOC_BOARD = [1, 4, 2, 3, 5, 8, 6, 7, 0]
DOC_SOLUTION = ["Down", "Right", "Down", "Right"]


def test_doc_board_discovery_host():
    checker = Puzzle(DOC_BOARD).checker().spawn_bfs().join()
    checker.assert_properties()
    checker.assert_discovery("solved", DOC_SOLUTION)


def test_doc_board_discovery_device():
    checker = (
        PackedPuzzle(DOC_BOARD)
        .checker()
        .spawn_xla(frontier_capacity=1 << 12, table_capacity=1 << 16)
    )
    while not checker.is_done():
        checker._run_block()
    checker.assert_properties()
    checker.assert_discovery("solved", DOC_SOLUTION)


def test_wrong_solution_rejected():
    checker = Puzzle(DOC_BOARD).checker().spawn_bfs().join()
    with pytest.raises(AssertionError):
        checker.assert_discovery("solved", ["Down", "Down"])


def test_2x2_unsolvable_full_coverage_parity():
    bad = [0, 2, 1, 3]  # the other 12-state component: solved unreachable
    host = Puzzle(bad, side=2).checker().spawn_bfs().join()
    dev = (
        PackedPuzzle(bad, side=2)
        .checker()
        .spawn_xla(frontier_capacity=1 << 8, table_capacity=1 << 10)
    )
    while not dev.is_done():
        dev._run_block()
    assert (host.state_count(), host.unique_state_count()) == (25, 12)
    assert (dev.state_count(), dev.unique_state_count()) == (25, 12)
    assert host.discovery("solved") is None
    assert dev.discovery("solved") is None


def test_pack_roundtrip():
    m = PackedPuzzle(DOC_BOARD)
    for s in (tuple(DOC_BOARD), tuple(range(9))):
        assert m.unpack(m.pack(s)) == s
