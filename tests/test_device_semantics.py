"""The generalized device serializer vs the host backtracking testers.

``semantics.device.device_serializable`` claims EXACT agreement with the
host ``BacktrackingTester`` search (the port of linearizability.rs:197-284 /
sequential_consistency.rs:127-225) for any statically-bounded history shape
under ``MAX_PATTERNS`` — over both specs (Register, WORegister) and both
consistency models (real_time=True/False). These tests fuzz random
protocol-valid histories (including invalid *semantics*: random returns) at
2x2, 3x2 and 3x3 shapes and require bit-for-bit verdict agreement; model
reachable-state differential coverage lives in
test_device_linearizability.py.
"""

import random

import numpy as np
import pytest

from stateright_tpu.actor.register import history_codecs
from stateright_tpu.packing import BoundedHistory, LayoutBuilder
from stateright_tpu.actor.write_once_register import wo_history_codecs
from stateright_tpu.semantics.device import (
    MAX_PATTERNS,
    DeviceRegister,
    DeviceWORegister,
    device_serializable,
    interleaving_tables,
    pattern_count,
)
from stateright_tpu.semantics.linearizability import LinearizabilityTester
from stateright_tpu.semantics.register import Read, ReadOk, Register, Write, WriteOk
from stateright_tpu.semantics.sequential_consistency import (
    SequentialConsistencyTester,
)
from stateright_tpu.semantics.write_once_register import (
    Read as WORead,
)
from stateright_tpu.semantics.write_once_register import (
    ReadOk as WOReadOk,
)
from stateright_tpu.semantics.write_once_register import (
    WORegister,
    WriteFail,
)
from stateright_tpu.semantics.write_once_register import (
    Write as WOWrite,
)
from stateright_tpu.semantics.write_once_register import (
    WriteOk as WOWriteOk,
)


# --- pattern table sanity ---------------------------------------------------


@pytest.mark.parametrize("T,slots", [(2, 3), (3, 3), (2, 4), (3, 4)])
def test_interleaving_tables_shape_and_uniqueness(T, slots):
    tid, slot, cnt_before = interleaving_tables(T, slots)
    P, L = tid.shape
    assert L == T * slots
    assert P == pattern_count(T, slots - 1)
    # Every pattern uses each thread exactly `slots` times, in slot order.
    assert len({tuple(r) for r in tid}) == P
    for t in range(T):
        assert (np.sum(tid == t, axis=1) == slots).all()
    rows = np.arange(P)
    running = np.zeros((P, T), dtype=np.int32)
    for l in range(L):
        assert (cnt_before[:, l, :] == running).all()
        assert (slot[:, l] == running[rows, tid[:, l]]).all()
        running[rows, tid[:, l]] += 1


def test_pattern_cap_raises_with_pointer_to_host_verified():
    # 4x2 (369,600) is device-exact since round 4 (chunked scan); the
    # refusal bound is now MAX_PATTERNS_EXACT — 5x2 = 1.68e8 exceeds it.
    b = LayoutBuilder()
    hist = BoundedHistory(
        b, thread_ids=[0, 1, 2, 3, 4], max_ops=2, op_bits=3, ret_bits=3
    )
    hist.bind(b.finish())
    words = np.zeros(hist.layout.words, dtype=np.uint32)
    with pytest.raises(NotImplementedError, match="host_verified_properties"):
        device_serializable(hist, words, DeviceRegister(), real_time=True)


# --- random-history differential fuzz --------------------------------------


def _random_events(rng, T, M, ops_of, rets_of):
    """A random protocol-valid event sequence: per thread at most M returns
    plus optionally one trailing in-flight invocation."""
    events = []
    n = [0] * T  # completed
    fl = [None] * T  # in-flight op
    budget = rng.randrange(1, 2 * T * (M + 1))
    while budget > 0:
        t = rng.randrange(T)
        if fl[t] is not None and n[t] < M and rng.random() < 0.6:
            events.append(("ret", t, rng.choice(rets_of(fl[t]))))
            n[t] += 1
            fl[t] = None
        elif fl[t] is None and n[t] + 1 <= M or (fl[t] is None and n[t] == M and rng.random() < 0.3):
            op = rng.choice(ops_of())
            events.append(("inv", t, op))
            fl[t] = op
        budget -= 1
    return events


def _replay(events, tester):
    for kind, t, x in events:
        if kind == "inv":
            tester.on_invoke(t, x)
        else:
            tester.on_return(t, x)
    return tester


def _device_verdicts(histories, T, M, op_bits, ret_bits, op_code, ret_code, spec, real_time):
    import jax
    import jax.numpy as jnp

    b = LayoutBuilder()
    hist = BoundedHistory(
        b, thread_ids=list(range(T)), max_ops=M, op_bits=op_bits, ret_bits=ret_bits
    )
    layout = b.finish()
    hist.bind(layout)
    words = np.stack(
        [
            layout.pack(**hist.from_tester(h, op_code, ret_code))
            for h in histories
        ]
    )
    fn = jax.jit(
        jax.vmap(lambda w: device_serializable(hist, w, spec, real_time=real_time))
    )
    return np.asarray(fn(jnp.asarray(words)))


@pytest.mark.parametrize(
    "T,M,trials",
    [
        (2, 2, 250),
        (3, 2, 250),
        (3, 3, 40),
        # 4x2 = 369,600 patterns: exercises the round-4 CHUNKED (lax.scan)
        # exact path — past the single-shot MAX_PATTERNS budget.
        (4, 2, 8),
    ],
)
@pytest.mark.parametrize("real_time", [True, False], ids=["lin", "seqcst"])
def test_register_fuzz_matches_host_serializer(T, M, trials, real_time):
    rng = random.Random(10_000 * T + 100 * M + real_time)
    values = [None] + [chr(ord("A") + k) for k in range(T)]
    op_code, _, ret_code, _ = history_codecs(values)
    ops_of = lambda: [Read()] + [Write(v) for v in values[1:]]
    rets_of = lambda op: (
        [ReadOk(v) for v in values] + [WriteOk()]
        if isinstance(op, Read)
        else [WriteOk()] + [ReadOk(v) for v in values]
    )
    make = (
        (lambda: LinearizabilityTester(Register(None)))
        if real_time
        else (lambda: SequentialConsistencyTester(Register(None)))
    )
    testers = [
        _replay(_random_events(rng, T, M, ops_of, rets_of), make())
        for _ in range(trials)
    ]
    got = _device_verdicts(
        testers, T, M, 3, 3, op_code, ret_code, DeviceRegister(), real_time
    )
    want = np.array([h.serialized_history() is not None for h in testers])
    assert (got == want).all(), (
        f"{int(np.sum(got != want))} disagreements; first: "
        f"{testers[int(np.argmax(got != want))].history_by_thread}"
    )
    assert want.any() and (~want).any()  # the fuzz hits both verdicts


@pytest.mark.parametrize("T,M,trials", [(2, 2, 250), (3, 2, 250)])
@pytest.mark.parametrize("real_time", [True, False], ids=["lin", "seqcst"])
def test_wo_register_fuzz_matches_host_serializer(T, M, trials, real_time):
    rng = random.Random(31_337 + 10_000 * T + 100 * M + real_time)
    values = [None] + [chr(ord("A") + k) for k in range(T)]
    op_code, _, ret_code, _ = wo_history_codecs(values)
    ops_of = lambda: [WORead()] + [WOWrite(v) for v in values[1:]]
    rets_of = lambda op: (
        [WOReadOk(v) for v in values] + [WOWriteOk(), WriteFail()]
        if isinstance(op, WORead)
        else [WOWriteOk(), WriteFail()] + [WOReadOk(v) for v in values]
    )
    make = (
        (lambda: LinearizabilityTester(WORegister(None)))
        if real_time
        else (lambda: SequentialConsistencyTester(WORegister(None)))
    )
    testers = [
        _replay(_random_events(rng, T, M, ops_of, rets_of), make())
        for _ in range(trials)
    ]
    got = _device_verdicts(
        testers, T, M, 3, 3, op_code, ret_code, DeviceWORegister(), real_time
    )
    want = np.array([h.serialized_history() is not None for h in testers])
    assert (got == want).all(), (
        f"{int(np.sum(got != want))} disagreements; first: "
        f"{testers[int(np.argmax(got != want))].history_by_thread}"
    )
    assert want.any() and (~want).any()


def test_seqcst_is_weaker_than_linearizability():
    # A history that is sequentially consistent but NOT linearizable:
    # thread 0 completes Write(A); afterwards thread 1 reads None (stale).
    # SC may reorder the read before the write; real time forbids it.
    h = LinearizabilityTester(Register(None))
    h.on_invoke(0, Write("A")).on_return(0, WriteOk())
    h.on_invoke(1, Read()).on_return(1, ReadOk(None))
    assert h.serialized_history() is None
    s = SequentialConsistencyTester(Register(None))
    s.on_invoke(0, Write("A")).on_return(0, WriteOk())
    s.on_invoke(1, Read()).on_return(1, ReadOk(None))
    assert s.serialized_history() is not None

    values = [None, "A", "B"]
    op_code, _, ret_code, _ = history_codecs(values)
    lin = _device_verdicts([h], 2, 2, 3, 3, op_code, ret_code, DeviceRegister(), True)
    sc = _device_verdicts([s], 2, 2, 3, 3, op_code, ret_code, DeviceRegister(), False)
    assert not lin[0] and sc[0]
