"""Actor-model semantics tests, ported from
/root/reference/src/actor/model.rs:569-998 (state-set equality, network
semantics matrix, ordered-delivery restriction, timer reset, undeliverable
messages) plus a duck-typed heterogeneous-actors test replacing the
reference's Choice machinery (model.rs:1001-1149)."""

from stateright_tpu import Expectation, PathRecorder, StateRecorder
from stateright_tpu.actor import (
    Actor,
    ActorModel,
    ActorModelState,
    DeliverAction,
    DropAction,
    Envelope,
    Id,
    Network,
    Timers,
    model_timeout,
)
from stateright_tpu.actor.actor_test_util import (
    Ping,
    PingPongCfg,
    Pong,
    ping_pong_model,
)


def _lossy_pp(max_nat, maintains_history=False):
    return (
        ping_pong_model(PingPongCfg(maintains_history, max_nat))
        .lossy_network(True)
    )


def test_visits_expected_states():
    def snap(states, envelopes):
        return ActorModelState(
            actor_states=tuple(states),
            network=Network.new_unordered_duplicating(envelopes),
            timers_set=(Timers(), Timers()),
            history=(0, 0),
        )

    def env(src, dst, msg):
        return Envelope(Id(src), Id(dst), msg)

    recorder, accessor = StateRecorder.new_with_accessor()
    checker = (
        _lossy_pp(max_nat=1)
        .checker()
        .visitor(recorder)
        .spawn_bfs()
        .join()
    )
    assert checker.unique_state_count() == 14
    state_space = accessor()
    assert len(state_space) == 14
    assert set(map(_freeze, state_space)) == set(
        map(
            _freeze,
            [
                # When the network loses no messages...
                snap([0, 0], [env(0, 1, Ping(0))]),
                snap([0, 1], [env(0, 1, Ping(0)), env(1, 0, Pong(0))]),
                snap(
                    [1, 1],
                    [env(0, 1, Ping(0)), env(1, 0, Pong(0)), env(0, 1, Ping(1))],
                ),
                # When the network loses the message for state (0, 0)...
                snap([0, 0], []),
                # When the network loses a message for state (0, 1)...
                snap([0, 1], [env(1, 0, Pong(0))]),
                snap([0, 1], [env(0, 1, Ping(0))]),
                snap([0, 1], []),
                # When the network loses a message for state (1, 1)...
                snap([1, 1], [env(1, 0, Pong(0)), env(0, 1, Ping(1))]),
                snap([1, 1], [env(0, 1, Ping(0)), env(0, 1, Ping(1))]),
                snap([1, 1], [env(0, 1, Ping(0)), env(1, 0, Pong(0))]),
                snap([1, 1], [env(0, 1, Ping(1))]),
                snap([1, 1], [env(1, 0, Pong(0))]),
                snap([1, 1], [env(0, 1, Ping(0))]),
                snap([1, 1], []),
            ],
        )
    )


def _freeze(state: ActorModelState):
    from stateright_tpu import fingerprint

    return fingerprint(state)


def test_maintains_fixed_delta_despite_lossy_duplicating_network():
    checker = _lossy_pp(max_nat=5).checker().spawn_bfs().join()
    assert checker.unique_state_count() == 4094
    checker.assert_no_discovery("delta within 1")


def test_may_never_reach_max_on_lossy_network():
    checker = _lossy_pp(max_nat=5).checker().spawn_bfs().join()
    assert checker.unique_state_count() == 4094
    # Can lose the first message and get stuck, for example.
    checker.assert_discovery(
        "must reach max", [DropAction(Envelope(Id(0), Id(1), Ping(0)))]
    )


def test_eventually_reaches_max_on_perfect_delivery_network():
    checker = (
        ping_pong_model(PingPongCfg(False, 5))
        .init_network(Network.new_unordered_nonduplicating())
        .checker()
        .spawn_bfs()
        .join()
    )
    assert checker.unique_state_count() == 11
    checker.assert_no_discovery("must reach max")


def test_can_reach_max():
    checker = ping_pong_model(PingPongCfg(False, 5)).checker().spawn_bfs().join()
    assert checker.unique_state_count() == 11
    assert checker.discovery("can reach max").last_state().actor_states == (4, 5)


def test_might_never_reach_beyond_max():
    checker = (
        ping_pong_model(PingPongCfg(False, 5))
        .init_network(Network.new_unordered_nonduplicating())
        .checker()
        .spawn_bfs()
        .join()
    )
    assert checker.unique_state_count() == 11
    # A liveness property failing due to the boundary.
    assert checker.discovery("must exceed max").last_state().actor_states == (5, 5)


def test_handles_undeliverable_messages():
    class Inert(Actor):
        def on_start(self, id, out):
            return ()

    checker = (
        ActorModel()
        .actor(Inert())
        .property(Expectation.ALWAYS, "unused", lambda _, s: True)
        .init_network(
            Network.new_unordered_duplicating([Envelope(Id(0), Id(99), ())])
        )
        .checker()
        .spawn_bfs()
        .join()
    )
    assert checker.unique_state_count() == 1


class _CountdownActor(Actor):
    """Sends 2 then 1 to actor 1, which appends what it receives."""

    def on_start(self, id, out):
        if id == Id(0):
            out.send(Id(1), 2)
            out.send(Id(1), 1)
        return ()

    def on_msg(self, id, state, src, msg, out):
        state.set(state.get() + (msg,))


def test_handles_ordered_network_flag():
    def recipient_states(network):
        recorder, accessor = StateRecorder.new_with_accessor()
        (
            ActorModel()
            .add_actors([_CountdownActor(), _CountdownActor()])
            .property(Expectation.ALWAYS, "", lambda _, s: True)
            .init_network(network)
            .checker()
            .visitor(recorder)
            .spawn_bfs()
            .join()
        )
        return [s.actor_states[1] for s in accessor()]

    # Fewer states if the network is ordered: only 2 then 1 deliverable.
    assert recipient_states(Network.new_ordered()) == [(), (2,), (2, 1)]
    # More states if unordered: both delivery orders occur. (The reference
    # asserts its hash-iteration order within BFS levels; only the level
    # structure is meaningful, so compare levels as sets.)
    unordered = recipient_states(Network.new_unordered_nonduplicating())
    assert unordered[0] == ()
    assert set(unordered[1:3]) == {(2,), (1,)}
    assert set(unordered[3:]) == {(2, 1), (1, 2)}


def test_unordered_network_has_a_bug():
    """Network-semantics matrix (model.rs:861-964): which action sequences
    exist across {ordered, unordered-dup, unordered-nondup} x {lossy,
    lossless}."""

    class A(Actor):
        def on_start(self, id, out):
            if id == Id(0):
                out.send(Id(1), "m")
                out.send(Id(1), "m")
            return 0

        def on_msg(self, id, state, src, msg, out):
            state.set(state.get() + 1)

    def action_sequences(lossy, network):
        recorder, accessor = PathRecorder.new_with_accessor()
        (
            ActorModel()
            .add_actors([A(), A()])
            .init_network(network)
            .lossy_network(lossy)
            .property(Expectation.ALWAYS, "force visiting all states", lambda _, s: True)
            .within_boundary_fn(lambda _, s: s.actor_states[1] < 4)
            .checker()
            .visitor(recorder)
            .spawn_dfs()
            .join()
        )
        return {tuple(p.into_actions()) for p in accessor()}

    deliver = DeliverAction(Id(0), Id(1), "m")
    drop = DropAction(Envelope(Id(0), Id(1), "m"))

    # Ordered networks can deliver/drop both messages.
    ordered_lossless = action_sequences(False, Network.new_ordered())
    assert (deliver, deliver) in ordered_lossless
    assert (deliver, deliver, deliver) not in ordered_lossless
    ordered_lossy = action_sequences(True, Network.new_ordered())
    assert (deliver, deliver) in ordered_lossy
    assert (deliver, drop) in ordered_lossy
    assert (drop, drop) in ordered_lossy

    # Unordered duplicating networks can deliver/drop duplicates; dropping
    # means "never deliver again".
    unord_dup_lossless = action_sequences(False, Network.new_unordered_duplicating())
    assert (deliver, deliver, deliver) in unord_dup_lossless
    unord_dup_lossy = action_sequences(True, Network.new_unordered_duplicating())
    assert (deliver, deliver, deliver) in unord_dup_lossy
    assert (deliver, deliver, drop) in unord_dup_lossy
    assert (deliver, drop) in unord_dup_lossy
    assert (drop,) in unord_dup_lossy
    assert (drop, deliver) not in unord_dup_lossy

    # Unordered nonduplicating networks can deliver/drop both messages.
    unord_nondup_lossless = action_sequences(
        False, Network.new_unordered_nonduplicating()
    )
    assert (deliver, deliver) in unord_nondup_lossless
    unord_nondup_lossy = action_sequences(True, Network.new_unordered_nonduplicating())
    assert (deliver, drop) in unord_nondup_lossy
    assert (drop, drop) in unord_nondup_lossy


def test_resets_timer():
    class TimerActor(Actor):
        def on_start(self, id, out):
            out.set_timer("t", model_timeout())
            return ()

    # Init state with timer, followed by next state without timer.
    checker = (
        ActorModel()
        .actor(TimerActor())
        .property(Expectation.ALWAYS, "unused", lambda _, s: True)
        .checker()
        .spawn_bfs()
        .join()
    )
    assert checker.unique_state_count() == 2


def test_heterogeneous_actor_systems_via_duck_typing():
    """Replaces the reference's Choice sum types (model.rs:1001-1149): in
    Python a model simply mixes actor classes."""

    class Server(Actor):
        def on_start(self, id, out):
            return 0

        def on_msg(self, id, state, src, msg, out):
            out.send(src, ("ack", msg))
            state.set(state.get() + 1)

    class Client(Actor):
        def on_start(self, id, out):
            out.send(Id(0), "req")
            return "waiting"

        def on_msg(self, id, state, src, msg, out):
            state.set("done")

    checker = (
        ActorModel()
        .actor(Server())
        .actor(Client())
        .init_network(Network.new_unordered_nonduplicating())
        .property(
            Expectation.SOMETIMES,
            "client done",
            lambda _, s: s.actor_states[1] == "done",
        )
        .checker()
        .spawn_bfs()
        .join()
    )
    checker.assert_properties()
    assert checker.discovery("client done").last_state().actor_states == (1, "done")


def test_script_actor_drives_system():
    """ScriptActor sends its pairs in sequence, one per delivery
    (actor.rs:495-527): against an echo server, a 2-message script reaches
    index 2 with both replies delivered."""
    from stateright_tpu.actor import Actor, ActorModel, Id, Network, ScriptActor
    from stateright_tpu.core import Expectation

    class Echo(Actor):
        def on_start(self, id, out):
            return 0

        def on_msg(self, id, state, src, msg, out):
            state.set(state.get() + 1)
            out.send(src, ("echo", msg))

    model = ActorModel(cfg=None)
    model.actor(Echo())
    model.actor(ScriptActor([(Id(0), "a"), (Id(0), "b")]))
    model = model.init_network(Network.new_unordered_nonduplicating()).property(
        Expectation.SOMETIMES,
        "script done",
        lambda _m, s: s.actor_states[1] == 2 and s.actor_states[0] == 2,
    )
    checker = model.checker().spawn_bfs().join()
    checker.assert_properties()
