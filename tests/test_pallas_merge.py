"""Exactness pins for the pallas streaming merge-insert
(ops/pallas_merge.py) in interpret mode — the CPU reference semantics
for the chip program (same contract as tests/test_pallas_compact.py).

Reference semantics: sortedset.insert's dedup rule
(/root/reference/src/checker/bfs.rs:247-259's visited-set insert,
generalized) — existing rows win over equal-key candidates, the lowest
batch index wins among in-batch duplicates, winners' values stored.
"""

import numpy as np
import pytest

import jax

jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp

from stateright_tpu.ops.pallas_merge import merge_insert

FULL = 0xFFFFFFFF
B, C, M = 256, 1024, 512


def _mk(rng, n_table, n_cand, key_space):
    tk = np.sort(rng.choice(key_space, n_table, replace=False)).astype(np.uint64)
    table = np.full((4, C), FULL, np.uint32)
    table[0, :n_table] = (tk >> 16).astype(np.uint32)
    table[1, :n_table] = (tk & 0xFFFF).astype(np.uint32)
    table[2, :n_table] = rng.integers(0, 2**32, n_table, dtype=np.uint32)
    table[3, :n_table] = rng.integers(0, 2**32, n_table, dtype=np.uint32)
    ck = rng.choice(key_space, n_cand, replace=True).astype(np.uint64)
    order = np.argsort(ck, kind="stable")
    batch = np.full((4, M), FULL, np.uint32)
    batch[0, :n_cand] = (ck >> 16).astype(np.uint32)[order]
    batch[1, :n_cand] = (ck & 0xFFFF).astype(np.uint32)[order]
    batch[2, :n_cand] = rng.integers(0, 2**32, n_cand, dtype=np.uint32)
    batch[3, :n_cand] = rng.integers(0, 2**32, n_cand, dtype=np.uint32)
    return table, batch


def _reference(table, batch, n_t, n_c):
    tkeys = (table[0, :n_t].astype(np.uint64) << 32) | table[1, :n_t]
    bkeys = (batch[0, :n_c].astype(np.uint64) << 32) | batch[1, :n_c]
    seen = set(tkeys.tolist())
    want_keep = np.zeros(M, bool)
    new = []
    for i in range(n_c):
        if int(bkeys[i]) not in seen:
            seen.add(int(bkeys[i]))
            want_keep[i] = True
            new.append((bkeys[i], batch[2, i], batch[3, i]))
    allk = np.concatenate(
        [tkeys, np.array([r[0] for r in new], np.uint64)]
    ) if new else tkeys
    vh = np.concatenate(
        [table[2, :n_t], np.array([r[1] for r in new], np.uint32)]
    ) if new else table[2, :n_t]
    vl = np.concatenate(
        [table[3, :n_t], np.array([r[2] for r in new], np.uint32)]
    ) if new else table[3, :n_t]
    o = np.argsort(allk, kind="stable")
    return want_keep, n_t + len(new), allk[o], vh[o], vl[o]


def _run(table, batch):
    mg, kb, nk = merge_insert(
        jnp.asarray(table), jnp.asarray(batch), block=B, interpret=True
    )
    return np.asarray(mg), np.asarray(kb), int(nk)


@pytest.mark.parametrize("trial", range(3))
def test_randomized_matches_reference(trial):
    rng = np.random.default_rng(100 + trial)
    n_t = int(rng.integers(0, 900))
    n_c = int(rng.integers(0, 500))
    ks = rng.choice(2**20, 2000, replace=False)
    table, batch = _mk(rng, n_t, n_c, ks)
    mg, kb, nk = _run(table, batch)
    want_keep, want_n, wk, wvh, wvl = _reference(table, batch, n_t, n_c)
    assert nk == want_n
    assert np.array_equal(kb, want_keep)
    gk = (mg[0, :want_n].astype(np.uint64) << 32) | mg[1, :want_n]
    assert np.array_equal(gk, wk)
    assert np.array_equal(mg[2, :want_n], wvh)
    assert np.array_equal(mg[3, :want_n], wvl)


def test_overflow_reports_total_and_flags_survive():
    rng = np.random.default_rng(3)
    tk = np.sort(rng.choice(2**20, 400, replace=False)).astype(np.uint64)
    ck = np.sort(np.setdiff1d(
        rng.choice(2**20, 400, replace=False).astype(np.uint64), tk
    )[:200])
    Cs = 512
    table = np.full((4, Cs), FULL, np.uint32)
    batch = np.full((4, M), FULL, np.uint32)
    table[0, :400] = (tk >> 16).astype(np.uint32)
    table[1, :400] = (tk & 0xFFFF).astype(np.uint32)
    batch[0, :200] = (ck >> 16).astype(np.uint32)
    batch[1, :200] = (ck & 0xFFFF).astype(np.uint32)
    mg, kb, nk = merge_insert(
        jnp.asarray(table), jnp.asarray(batch), block=B, interpret=True
    )
    assert int(nk) == 600 > Cs  # caller's grow-and-retry signal
    kb = np.asarray(kb)
    assert kb[:200].all() and not kb[200:].any()


def test_insert_via_merge_matches_sort_lowering(monkeypatch):
    """sortedset.insert under STPU_SORTEDSET_INSERT=pallas is
    bit-identical to the sort lowering: table planes, n, is_new (batch
    order), overflow."""
    from stateright_tpu.ops import sortedset

    rng = np.random.default_rng(11)
    cap, m = 512, 256
    monkeypatch.setenv("STPU_PALLAS_BLOCK", "64")
    for trial in range(3):
        n0 = int(rng.integers(0, cap // 2))
        keys = rng.choice(2**18, n0 + m, replace=False).astype(np.uint64)
        ss = sortedset.from_entries(
            jnp.asarray((keys[:n0] >> 8).astype(np.uint32)),
            jnp.asarray((keys[:n0] & 0xFF).astype(np.uint32)),
            jnp.asarray(rng.integers(0, 2**32, n0, dtype=np.uint32)),
            jnp.asarray(rng.integers(0, 2**32, n0, dtype=np.uint32)),
            cap,
            jnp,
        )
        # Batch: half fresh keys, half dups of table keys, some inactive.
        pick = rng.integers(0, n0 + m, m)
        bh = jnp.asarray((keys[pick] >> 8).astype(np.uint32))
        bl = jnp.asarray((keys[pick] & 0xFF).astype(np.uint32))
        vh = jnp.asarray(rng.integers(0, 2**32, m, dtype=np.uint32))
        vl = jnp.asarray(rng.integers(0, 2**32, m, dtype=np.uint32))
        act = jnp.asarray(rng.integers(0, 4, m) > 0)

        monkeypatch.setattr(sortedset, "INSERT_VIA", "sort")
        ss_a, new_a, ovf_a = sortedset.insert(ss, bh, bl, vh, vl, act)
        monkeypatch.setattr(sortedset, "INSERT_VIA", "pallas")
        ss_b, new_b, ovf_b = sortedset.insert(ss, bh, bl, vh, vl, act)

        assert int(ss_a.n) == int(ss_b.n), trial
        assert bool(ovf_a) == bool(ovf_b), trial
        assert np.array_equal(np.asarray(new_a), np.asarray(new_b)), trial
        for fa, fb in zip(ss_a[:4], ss_b[:4]):
            assert np.array_equal(np.asarray(fa), np.asarray(fb)), trial

        # Non-block-divisible batch falls through to the sort lowering
        # bit-identically (the gate's documented convention).
        odd = m - 56
        ss_c, new_c, ovf_c = sortedset.insert(
            ss, bh[:odd], bl[:odd], vh[:odd], vl[:odd], act[:odd]
        )
        monkeypatch.setattr(sortedset, "INSERT_VIA", "sort")
        ss_d, new_d, ovf_d = sortedset.insert(
            ss, bh[:odd], bl[:odd], vh[:odd], vl[:odd], act[:odd]
        )
        assert int(ss_c.n) == int(ss_d.n), trial
        assert np.array_equal(np.asarray(new_c), np.asarray(new_d)), trial


def test_engine_via_merge_matches(monkeypatch):
    """Full-engine differential: counts AND witness paths equal under
    the merge-insert lowering (same contract as the compaction modes,
    tests/test_sortedset.py)."""
    from stateright_tpu.models.two_phase_commit import PackedTwoPhaseSys
    from stateright_tpu.ops import sortedset

    kw = dict(frontier_capacity=1 << 7, table_capacity=1 << 9, dedup="sorted")
    a = PackedTwoPhaseSys(3).checker().spawn_xla(**kw).join()
    da = a.discoveries()
    assert da
    monkeypatch.setenv("STPU_PALLAS_BLOCK", "64")
    monkeypatch.setattr(sortedset, "INSERT_VIA", "pallas")
    b = PackedTwoPhaseSys(3).checker().spawn_xla(**kw).join()
    assert (a.state_count(), a.unique_state_count()) == (
        b.state_count(),
        b.unique_state_count(),
    )
    db = b.discoveries()
    assert set(da) == set(db)
    for name in da:
        assert da[name].into_states() == db[name].into_states()


def test_edge_empty_and_dup_runs():
    rng = np.random.default_rng(5)
    tk = np.sort(rng.choice(2**20, 300, replace=False)).astype(np.uint64)
    table = np.full((4, C), FULL, np.uint32)
    table[0, :300] = (tk >> 16).astype(np.uint32)
    table[1, :300] = (tk & 0xFFFF).astype(np.uint32)
    empty_b = np.full((4, M), FULL, np.uint32)
    _, kb, nk = _run(table, empty_b)
    assert nk == 300 and not kb.any()
    empty_t = np.full((4, C), FULL, np.uint32)
    _, kb, nk = _run(empty_t, empty_b)
    assert nk == 0 and not kb.any()
    # One absent key repeated across a block boundary: single winner,
    # lowest batch index (its value), exercises the SMEM key-carry.
    batch = np.full((4, M), FULL, np.uint32)
    batch[0, :300] = 5
    batch[1, :300] = 9
    batch[2, :300] = np.arange(300, dtype=np.uint32)
    mg, kb, nk = _run(table, batch)
    assert nk == 301
    assert kb[0] and not kb[1:].any()
    keys = (mg[0, :301].astype(np.uint64) << 32) | mg[1, :301]
    pos = int(np.searchsorted(keys, (np.uint64(5) << np.uint64(32)) | np.uint64(9)))
    assert mg[2, pos] == 0
