"""XLA engine differential tests: the device frontier-expansion engine must
reproduce the CPU oracle's unique-state counts and produce valid witness
paths (the differential-testing strategy of SURVEY.md section 7)."""

import numpy as np
import pytest

import jax.numpy as jnp

from stateright_tpu import Property
from stateright_tpu.models.two_phase_commit import PackedTwoPhaseSys
from stateright_tpu.ops import fphash, hashset
from stateright_tpu.test_util import DGraph, PackedDGraph


# --- ops ------------------------------------------------------------------


def test_fphash_host_device_agree():
    words = np.random.default_rng(0).integers(0, 2**32, size=(64, 2), dtype=np.uint32)
    h_hi, h_lo = fphash.fingerprint_words(words, np)
    d_hi, d_lo = fphash.fingerprint_words(jnp.asarray(words), jnp)
    np.testing.assert_array_equal(h_hi, np.asarray(d_hi))
    np.testing.assert_array_equal(h_lo, np.asarray(d_lo))
    # 64 distinct inputs -> 64 distinct fingerprints (collision would be 2^-64).
    assert len({(int(a), int(b)) for a, b in zip(h_hi, h_lo)}) == 64


def test_hashset_insert_dedup_and_lookup():
    hs = hashset.make(256, jnp)
    rng = np.random.default_rng(1)
    fp_hi = jnp.asarray(rng.integers(1, 2**32, size=100, dtype=np.uint32))
    fp_lo = jnp.asarray(rng.integers(1, 2**32, size=100, dtype=np.uint32))
    vals = jnp.asarray(np.arange(1, 101, dtype=np.uint32))
    active = jnp.ones(100, bool)
    hs, is_new, ovf = hashset.insert(hs, fp_hi, fp_lo, vals, vals, active)
    assert int(is_new.sum()) == 100 and not bool(ovf.any())
    # Re-insert: all duplicates.
    hs, is_new2, ovf2 = hashset.insert(hs, fp_hi, fp_lo, vals, vals, active)
    assert int(is_new2.sum()) == 0 and not bool(ovf2.any())
    found, vh, _ = hashset.lookup(hs, fp_hi, fp_lo)
    assert bool(found.all())
    np.testing.assert_array_equal(np.asarray(vh), np.asarray(vals))


def test_hashset_in_batch_duplicates_elect_one_winner():
    hs = hashset.make(64, jnp)
    fp_hi = jnp.asarray(np.array([7, 7, 7, 9], dtype=np.uint32))
    fp_lo = jnp.asarray(np.array([1, 1, 1, 2], dtype=np.uint32))
    vals = jnp.asarray(np.array([10, 20, 30, 40], dtype=np.uint32))
    hs, is_new, ovf = hashset.insert(hs, fp_hi, fp_lo, vals, vals, jnp.ones(4, bool))
    assert not bool(ovf.any())
    assert np.asarray(is_new).tolist() == [True, False, False, True]
    # Winner is the lowest batch index: value 10 stored for key (7,1).
    found, vh, _ = hashset.lookup(hs, fp_hi[:1], fp_lo[:1])
    assert bool(found[0]) and int(vh[0]) == 10


def test_hashset_inactive_lanes_ignored():
    hs = hashset.make(64, jnp)
    fp = jnp.asarray(np.array([5, 6], dtype=np.uint32))
    hs, is_new, _ = hashset.insert(
        hs, fp, fp, fp, fp, jnp.asarray(np.array([True, False]))
    )
    assert np.asarray(is_new).tolist() == [True, False]
    found, _, _ = hashset.lookup(hs, fp, fp)
    assert np.asarray(found).tolist() == [True, False]


def test_hashset_false_claim_conflicts_resolve():
    # Batch-proportional election: the claim buffer is ~2*batch slots, so
    # distinct table slots can share a claim index (here m=4 => claim_cap=16;
    # slots 3 and 19 collide at index 3). The loser must retry and land on
    # the next round — both keys insert, deterministically.
    hs = hashset.make(1 << 12, jnp)

    def fp_for_slot(slot, cap=1 << 12):
        # slot = (hi ^ (lo * 0x9E3779B1)) & (cap-1); pick lo=0 => slot = hi & mask.
        return np.uint32(slot), np.uint32(0)

    pairs = [fp_for_slot(s) for s in (3, 19, 3 + 16 * 7, 1024 + 3)]
    fp_hi = jnp.asarray(np.array([p[0] for p in pairs], dtype=np.uint32))
    fp_lo = jnp.asarray(np.array([p[1] for p in pairs], dtype=np.uint32))
    vals = jnp.asarray(np.array([1, 2, 3, 4], dtype=np.uint32))
    hs, is_new, ovf = hashset.insert(hs, fp_hi, fp_lo, vals, vals, jnp.ones(4, bool))
    assert np.asarray(is_new).tolist() == [True] * 4
    assert not bool(ovf.any())
    found, vh, _ = hashset.lookup(hs, fp_hi, fp_lo)
    assert bool(found.all())
    np.testing.assert_array_equal(np.asarray(vh), np.asarray(vals))


def test_hashset_claim_buffer_is_batch_proportional():
    # The jaxpr of an insert into a huge table must not materialize any
    # O(capacity) temporary besides the table planes themselves: the claim
    # buffer must be sized by the batch (here 2*64=128), not the 2^22 table.
    import jax

    cap = 1 << 22
    m = 64
    hs = hashset.make(cap, jnp)
    args = (
        jnp.ones(m, jnp.uint32),
        jnp.arange(1, m + 1, dtype=jnp.uint32),
        jnp.zeros(m, jnp.uint32),
        jnp.zeros(m, jnp.uint32),
        jnp.ones(m, bool),
    )
    jaxpr = jax.make_jaxpr(lambda t, *a: hashset.insert(t, *a))(hs, *args)
    big = 0
    for eqn in jaxpr.jaxpr.eqns:
        for v in eqn.outvars:
            shape = getattr(getattr(v, "aval", None), "shape", None)
            if shape == (cap,) and eqn.primitive.name == "broadcast_in_dim":
                big += 1
    # The four table planes flow through while_loop untouched; no fresh
    # [capacity] broadcast may appear (the old design created one per call).
    assert big == 0, f"found {big} O(capacity) temporaries in insert jaxpr"


def test_hashset_overflow_reported():
    hs = hashset.make(8, jnp)
    rng = np.random.default_rng(2)
    fp_hi = jnp.asarray(rng.integers(1, 2**32, size=32, dtype=np.uint32))
    fp_lo = jnp.asarray(rng.integers(1, 2**32, size=32, dtype=np.uint32))
    z = jnp.zeros(32, jnp.uint32)
    hs, is_new, ovf = hashset.insert(hs, fp_hi, fp_lo, z, z, jnp.ones(32, bool))
    assert int(is_new.sum()) == 8  # table filled
    assert bool(ovf.any())  # the rest reported as overflow, loudly


# --- engine: 2pc differential against the CPU oracle ----------------------


def test_xla_2pc_rm3_matches_oracle():
    checker = PackedTwoPhaseSys(3).checker().spawn_xla(
        frontier_capacity=1 << 10, table_capacity=1 << 12
    ).join()
    assert checker.unique_state_count() == 288  # == spawn_bfs (2pc.rs:156)
    checker.assert_properties()
    # Witness paths reconstructed from the device parent table must be valid
    # discoveries for their properties.
    for name in ("abort agreement", "commit agreement"):
        path = checker.discovery(name)
        checker.assert_discovery(name, path.into_actions())


def test_xla_2pc_rm5_matches_oracle():
    checker = PackedTwoPhaseSys(5).checker().spawn_xla(
        frontier_capacity=1 << 12, table_capacity=1 << 14
    ).join()
    assert checker.unique_state_count() == 8832  # == spawn_dfs (2pc.rs:161)
    checker.assert_properties()


def test_xla_2pc_rm5_symmetry():
    checker = (
        PackedTwoPhaseSys(5)
        .checker()
        .symmetry()
        .spawn_xla(frontier_capacity=1 << 12, table_capacity=1 << 14)
        .join()
    )
    # The model ships a symmetry_spec (stateright_tpu/sym), so the builder
    # request resolves to the spec-compiled FULL canonicalization — a
    # class-invariant kernel whose visited count equals the number of
    # reachable equivalence classes on ANY traversal (docs/symmetry.md).
    # 314 is the rm=5 class count; the reference's 665 (2pc.rs:170) is a
    # DFS-traversal artifact of its *partial* rm_state sort (ties keep
    # index order; our CPU DFS reproduces it — tests/test_symmetry.py).
    assert checker.unique_state_count() == 314
    checker.assert_properties()


def test_packed_representative_matches_object_representative():
    import jax

    m = PackedTwoPhaseSys(4)
    seen = set()
    stack = list(m.init_states())
    while stack:
        s = stack.pop()
        if s in seen:
            continue
        seen.add(s)
        stack.extend(m.next_states(s))
    states = list(seen)
    packed = np.stack([m.pack(s) for s in states])
    dev = np.asarray(jax.jit(jax.vmap(m.packed_representative))(jnp.asarray(packed)))
    obj = np.stack([m.pack(s.representative()) for s in states])
    np.testing.assert_array_equal(dev, obj)


def test_xla_capacity_autogrowth():
    # Deliberately tiny capacities: the engine must grow/rehash, not fail.
    checker = PackedTwoPhaseSys(3).checker().spawn_xla(
        frontier_capacity=1 << 4, table_capacity=1 << 4
    ).join()
    assert checker.unique_state_count() == 288
    checker.assert_properties()


def test_xla_state_count_matches_oracle_on_full_enumeration():
    # "consistent" (always) is never violated, so both engines explore the
    # full space; total generated-state counts must then agree exactly.
    from stateright_tpu.models.two_phase_commit import TwoPhaseSys

    cpu = TwoPhaseSys(3).checker().spawn_bfs().join()
    xla = PackedTwoPhaseSys(3).checker().spawn_xla().join()
    assert xla.unique_state_count() == cpu.unique_state_count()
    assert xla.state_count() == cpu.state_count()
    assert xla.max_depth() == cpu.max_depth()


# --- engine: eventually semantics on device (checker.rs:549-641) ----------


def eventually_odd() -> Property:
    return Property.eventually("odd", lambda _, s: s % 2 == 1)


def _xla_check(graph: DGraph):
    return PackedDGraph(graph).checker().spawn_xla(
        frontier_capacity=1 << 8, table_capacity=1 << 10
    ).join()


def test_xla_eventually_can_validate():
    g = (
        DGraph.with_property(eventually_odd())
        .with_path([1])
        .with_path([2, 3])
        .with_path([2, 6, 7])
        .with_path([4, 9, 10])
    )
    _xla_check(g).assert_properties()


def test_xla_eventually_can_discover_counterexample():
    c = _xla_check(DGraph.with_property(eventually_odd()).with_path([0, 1]).with_path([0, 2]))
    assert c.discovery("odd").into_states() == [0, 2]

    c = _xla_check(DGraph.with_property(eventually_odd()).with_path([0, 1]).with_path([2, 4]))
    assert c.discovery("odd").into_states() == [2, 4]

    c = _xla_check(
        DGraph.with_property(eventually_odd()).with_path([0, 1, 4, 6]).with_path([2, 4, 8])
    )
    assert c.discovery("odd").into_states() == [2, 4, 6]


def test_xla_eventually_false_negative_semantics_replicated():
    # Cycle/DAG-join false negatives are part of the reference contract
    # (checker.rs:623-640); the device engine replicates them.
    c = _xla_check(DGraph.with_property(eventually_odd()).with_path([0, 2, 4, 2]))
    assert c.discovery("odd") is None

    c = _xla_check(
        DGraph.with_property(eventually_odd()).with_path([0, 2, 4]).with_path([1, 4, 6])
    )
    assert c.discovery("odd") is None


def test_xla_target_max_depth():
    checker = (
        PackedTwoPhaseSys(3)
        .checker()
        .target_max_depth(3)
        .spawn_xla(frontier_capacity=1 << 10, table_capacity=1 << 12)
        .join()
    )
    assert checker.is_done()
    assert checker.max_depth() == 3


def test_learned_capacities_apply_to_defaults_only():
    """Growth events record capacity hints on the model, but a hint may only
    raise DEFAULT capacities: an explicit (even smaller) request wins, so a
    caller can deliberately exercise the growth path. Consumers that want
    hint-carryover with explicit capacities merge the hints themselves
    (bench.py does)."""
    from stateright_tpu.models.two_phase_commit import PackedTwoPhaseSys

    model = PackedTwoPhaseSys(4)
    a = model.checker().spawn_xla(frontier_capacity=1 << 10, table_capacity=1 << 8)
    a.join()
    assert a._table.capacity > (1 << 8)  # 1,568 uniques forced growth
    assert model.__dict__["_xla_table_cap_hint_hash"] == a._table.capacity
    # Explicit small capacity is honored verbatim despite the hint.
    b = model.checker().spawn_xla(frontier_capacity=1 << 10, table_capacity=1 << 8)
    assert b._table.capacity == 1 << 8
    b.join()
    assert b.unique_state_count() == a.unique_state_count()
    # Default capacities pick the hint up when it exceeds them.
    model.__dict__["_xla_table_cap_hint_hash"] = 1 << 21
    c = model.checker().spawn_xla(frontier_capacity=1 << 10)
    assert c._table.capacity == 1 << 21
