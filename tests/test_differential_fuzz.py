"""Randomized differential testing: every engine must agree on random graphs.

Seeded random DGraphs (the checker-semantics fixture) run on the sequential
BFS oracle, sequential DFS, the multiprocess BFS (threads(n)), the XLA
engine, and the fingerprint-sharded XLA engine. With an unreachable
``sometimes`` property the search exhausts the space, so generated/unique
counts and max depth are exploration-order-independent and must match
EXACTLY across engines. A second pass with an ``eventually`` property checks
discovery agreement (early-exit counts are order-dependent by design, so
only the discovery itself is compared).
"""

import random

import jax
import pytest

from stateright_tpu.core import Property
from stateright_tpu.test_util import DGraph, PackedDGraph

KW = dict(frontier_capacity=1 << 10, table_capacity=1 << 13)


def _random_graph(rng: random.Random) -> DGraph:
    n_nodes = rng.randint(4, 36)
    g = DGraph.with_property(
        Property.sometimes("unreachable", lambda _m, _s: False)
    )
    for _ in range(rng.randint(1, 5)):
        length = rng.randint(1, 6)
        g = g.with_path([rng.randrange(n_nodes) for _ in range(length)])
    return g


@pytest.mark.parametrize("seed", range(12))
def test_engines_agree_on_random_graphs(seed):
    rng = random.Random(1000 + seed)
    g = _random_graph(rng)
    oracle = g.checker().spawn_bfs().join()
    expect = (
        oracle.state_count(),
        oracle.unique_state_count(),
        oracle.max_depth(),
    )

    # DFS agrees on counts; its max_depth is visit-order-dependent (a DFS
    # may reach a state via a longer path first — true of the reference
    # too), so only BFS-family engines compare depths.
    dfs = g.checker().spawn_dfs().join()
    assert (dfs.state_count(), dfs.unique_state_count()) == expect[:2]
    assert dfs.max_depth() >= expect[2]

    par = g.checker().threads(3).spawn_bfs().join()
    assert (par.state_count(), par.unique_state_count(), par.max_depth()) == expect

    packed = PackedDGraph(g)
    dev = packed.checker().spawn_xla(**KW).join()
    assert (dev.state_count(), dev.unique_state_count(), dev.max_depth()) == expect

    if len(jax.devices()) >= 8:
        from stateright_tpu.parallel import default_mesh

        sh = PackedDGraph(g).checker().spawn_xla(mesh=default_mesh(8), **KW).join()
        assert (
            sh.state_count(),
            sh.unique_state_count(),
            sh.max_depth(),
        ) == expect


@pytest.mark.parametrize("seed", range(6))
def test_engines_agree_on_eventually_discoveries(seed):
    rng = random.Random(2000 + seed)
    n_nodes = rng.randint(4, 24)
    g = DGraph.with_property(
        Property.eventually("odd", lambda _m, s: s % 2 == 1)
    )
    for _ in range(rng.randint(1, 4)):
        length = rng.randint(1, 5)
        g = g.with_path([rng.randrange(n_nodes) for _ in range(length)])

    oracle = g.checker().spawn_bfs().join()
    names = set(oracle.discoveries())

    par = g.checker().threads(2).spawn_bfs().join()
    assert set(par.discoveries()) == names

    dev = PackedDGraph(g).checker().spawn_xla(**KW).join()
    assert set(dev.discoveries()) == names
    for name, path in dev.discoveries().items():
        # A counterexample must be a terminal even state in both engines.
        assert path.last_state() % 2 == 0
        assert oracle.discoveries()[name].last_state() % 2 == 0
