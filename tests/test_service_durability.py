"""Durable CheckerService pins (ISSUE 12 acceptance).

The pool must survive its own death: a crash-safe job journal
(``service/journal.py``), restart recovery in ``CheckerService``
(re-adopt checkpoints, requeue in-flight work, dedupe resubmissions,
restore the breaker), and the deterministic fault-injection layer
(``stateright_tpu/chaos.py``) that drives every one of those paths on a
seeded schedule instead of hand-rolled signals.

- **Journal discipline**: sha256-per-record appends; a tail torn at a
  RANDOM byte is a typed, recoverable condition — replay succeeds minus
  the torn record; compaction rewrites the log as one snapshot,
  atomically, with keep-K rotations.
- **Restart recovery** (no workers needed — the journal is the
  contract): journal-complete jobs restore done without re-running;
  idempotent resubmission after a restart returns the SAME job; an
  in-flight job whose budget was already spent fails typed, not re-run;
  a restored-open breaker re-probes immediately.
- **Chaos layer**: zero overhead with ``STPU_CHAOS`` unset (pinned);
  seeded plans fire deterministically; the ``checkpoint.torn`` hook
  tears a real rotation that ``latest_valid_checkpoint`` then falls
  back from; ``supervise.wedge`` draws a scripted wedge verdict.
- **Restart drills** (the real service, killed for real):
  ``test_smoke_service_restart_resume`` (<30s, rides in
  ``tools/smoke.sh``) — the service dies right after journaling
  ``started``, the restart kills the orphaned worker, requeues, and
  converges to exact pinned counts; the <60s 3-concurrent-job SIGKILL
  and torn-tail convergence pins ride the ``tools/service_chaos.py``
  harness (exactly-once, counts bit-identical to the undisturbed run).
"""

import importlib.util
import json
import os
import random
import time

import pytest

from stateright_tpu import chaos
from stateright_tpu.service import (
    AdmissionError,
    CheckerService,
    FleetConfig,
    FleetService,
    Journal,
    JournalTorn,
    ServiceConfig,
    read_journal,
)
from stateright_tpu.service.core import _replay_state
from stateright_tpu.service.fleet import _fleet_replay

#: Pinned full-coverage (generated, unique) counts (bench.py EXPECTED_*).
PINNED_2PC3 = (1_146, 288)


def _harness():
    """tools/service_chaos.py as an importable module (the harness the
    restart drills drive; same trick test_analysis uses for warm_cache)."""
    path = os.path.join(
        os.path.dirname(__file__), "..", "tools", "service_chaos.py"
    )
    spec = importlib.util.spec_from_file_location("service_chaos", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(autouse=True)
def _no_ambient_chaos(monkeypatch):
    """Each test starts with no installed plan and no STPU_CHAOS."""
    monkeypatch.delenv("STPU_CHAOS", raising=False)
    chaos.install(None)
    yield
    chaos.install(None)


def _config(tmp_path, **kw):
    base = dict(
        run_dir=str(tmp_path / "svc"),
        platform="cpu",
        default_max_seconds=420.0,
        stall_s=8.0,
        startup_grace_s=240.0,
        poll_s=0.2,
        backoff_s=0.1,
        probe_auto=False,
        admission_lint=False,
    )
    base.update(kw)
    return ServiceConfig(**base)


# --- the journal ------------------------------------------------------------


def test_journal_round_trip_and_digests(tmp_path):
    path = str(tmp_path / "j.jsonl")
    j = Journal(path)
    for i in range(4):
        rec = j.append("submitted", ts=100.0 + i, job=f"job-{i:04d}",
                       spec="2pc:3")
        assert rec["seq"] == i + 1 and rec["sha256"]
    replay = read_journal(path)
    assert replay.torn is None
    assert [r["job"] for r in replay.records] == [
        f"job-{i:04d}" for i in range(4)
    ]
    # A tampered mid-file record fails its digest: replay stops there,
    # typed — nothing after an untrusted record can be ordered.
    lines = open(path).read().splitlines()
    lines[1] = lines[1].replace("2pc:3", "2pc:9")
    (tmp_path / "j.jsonl").write_text("\n".join(lines) + "\n")
    tampered = read_journal(path)
    assert len(tampered.records) == 1
    assert "digest mismatch" in tampered.torn
    with pytest.raises(JournalTorn):
        read_journal(path, strict=True)


def test_journal_torn_tail_at_random_byte(tmp_path):
    """Truncate the journal at a RANDOM byte: replay returns the clean
    prefix and reports the torn tail — never raises, never wedges."""
    rng = random.Random(1234)
    for _ in range(8):
        path = str(tmp_path / f"j{rng.randint(0, 1 << 30)}.jsonl")
        j = Journal(path)
        for i in range(5):
            j.append("submitted", ts=float(i), job=f"job-{i:04d}", spec="s")
        j.close()
        data = open(path, "rb").read()
        cut = rng.randint(1, len(data) - 1)
        with open(path, "wb") as fh:
            fh.write(data[:cut])
        replay = read_journal(path)
        # Whole records before the cut replay; at most one record is
        # lost. A cut exactly ON a record boundary leaves no torn
        # evidence (the file just ends earlier) — every mid-record cut
        # is reported.
        complete = data[:cut].count(b"\n")
        assert len(replay.records) == complete
        assert (replay.torn is None) == data[:cut].endswith(b"\n")


def test_journal_compaction_snapshot_and_rotation(tmp_path):
    path = str(tmp_path / "j.jsonl")
    j = Journal(path, keep=2, compact_every=3)
    for i in range(3):
        j.append("submitted", ts=float(i), job=f"job-{i:04d}", spec="s")
    assert j.compaction_due
    j.compact({"next_id": 3, "jobs": {}}, ts=3.0)
    assert not j.compaction_due
    live = read_journal(path)
    assert [r["event"] for r in live.records] == ["snapshot"]
    assert live.records[0]["state"]["next_id"] == 3
    # The pre-compaction history rotated to .1, intact.
    rot = read_journal(path + ".1")
    assert [r["event"] for r in rot.records] == ["submitted"] * 3
    # seq is contiguous across the compaction boundary.
    assert live.records[0]["seq"] == 4


def test_replay_state_folds_snapshot_and_events():
    records = []

    def rec(event, **kw):
        r = {"v": 1, "seq": len(records) + 1, "event": event, **kw}
        records.append(r)
        return r

    rec("submitted", ts=1.0, job="job-0001", spec="2pc:3",
        max_seconds=60.0, idempotency_key="k1", dir="s/job-0001")
    rec("admitted", ts=1.0, job="job-0001", lint_ok=None)
    rec("started", ts=2.0, job="job-0001", attempt=0, engine="xla", pid=999)
    rec("breaker_tripped", ts=3.0, consecutive=3)
    rec("completed", ts=4.0, job="job-0001", status="done", error=None,
        result={"generated": 10, "unique": 5})
    state = _replay_state(records)
    assert state["breaker"] == "open"
    assert state["idem"] == {"k1": "job-0001"}
    job = state["jobs"]["job-0001"]
    assert job["status"] == "done" and job["completed_unix_ts"] == 4.0
    assert job["result"]["generated"] == 10
    assert state["counters"]["jobs_done"] == 1
    assert state["counters"]["breaker_trips"] == 1
    assert state["last_ts"] == 4.0


def test_replay_evacuated_carries_the_attempt_charge():
    """The `evacuated` event journals the killed attempt's wall-clock: a
    crash between the pool's `evacuated` append and the fleet's
    `migrated` append must not refund the budget the straggler repair
    resubmits with (evacuate() charges in memory AND in the event)."""
    records = []

    def rec(event, **kw):
        r = {"v": 1, "seq": len(records) + 1, "event": event, **kw}
        records.append(r)
        return r

    rec("submitted", ts=1.0, job="job-0001", spec="2pc:3",
        max_seconds=60.0, dir="s/job-0001")
    rec("admitted", ts=1.0, job="job-0001", lint_ok=None)
    rec("started", ts=2.0, job="job-0001", attempt=0, engine="xla", pid=999)
    rec("evacuated", ts=52.0, job="job-0001", reason="device-0 lost",
        consumed_s=50.0)
    state = _replay_state(records)
    job = state["jobs"]["job-0001"]
    assert job["status"] == "migrated"
    assert job["consumed_s"] == 50.0
    assert job["pid"] is None  # the worker group was killed, no orphan
    # A `started` journaled AFTER `evacuated` (the spawn/evacuate race's
    # window) must not resurrect the evacuated job as running — the
    # sibling pool owns the live copy.
    rec("started", ts=52.5, job="job-0001", attempt=1, engine="xla",
        pid=1000)
    state = _replay_state(records)
    job = state["jobs"]["job-0001"]
    assert job["status"] == "migrated" and job["pid"] is None


def test_harness_schedule_and_faults_are_seed_deterministic():
    """`tools/service_chaos.py --seed N` is reproducible: the submission
    schedule and the fault plan are pure functions of the seed (the full
    journal-event-sequence pin is the harness's own --check-repro)."""
    sc = _harness()
    assert sc.build_schedule(7, 3, 240.0) == sc.build_schedule(7, 3, 240.0)
    assert sc.build_schedule(7, 3, 240.0) != sc.build_schedule(8, 3, 240.0)
    for scenario in ("kill", "die", "torn"):
        assert sc.fault_plan(7, scenario) == sc.fault_plan(7, scenario)
    # Golden values pin CROSS-PROCESS stability (a per-process
    # within-run comparison would be blind to PYTHONHASHSEED-style
    # randomization — the bug the crc32 seed derivation fixed).
    assert sc.fault_plan(42, "kill") == {"kill_after_s": 4.861}
    assert sc.fault_plan(42, "die") == {"die_at_record": 9}
    assert sc.fault_plan(42, "torn") == {"torn_at_record": 6}


# --- the chaos layer --------------------------------------------------------


def test_chaos_off_is_a_noop():
    """The zero-overhead-off pin (like the obs NULL_TRACER guard): with
    STPU_CHAOS unset nothing is parsed, no plan exists, and every hook
    call is a fast None."""
    assert chaos.fire("journal.torn", size=100) is None
    assert chaos.fire("supervise.wedge") is None
    assert not chaos.active()
    assert chaos._PLAN is None  # no ChaosPlan was ever constructed


def test_chaos_plan_parse_and_triggers():
    plan = chaos.ChaosPlan("seed=9;a.b@n=2:at=17,mode=x;c.d@p=0.5;e.f")
    assert plan.seed == 9
    # @n=K: exactly the K-th invocation.
    assert plan.fire("a.b") is None
    assert plan.fire("a.b") == {"at": 17, "mode": "x"}
    assert plan.fire("a.b") is None
    # no trigger: every invocation.
    assert plan.fire("e.f") == {}
    assert plan.fire("e.f") == {}
    # unknown point: never.
    assert plan.fire("nope") is None
    # @p=F: seeded — two plans from the same spec agree exactly.
    twin = chaos.ChaosPlan("seed=9;a.b@n=2:at=17,mode=x;c.d@p=0.5;e.f")
    seq = [plan.fire("c.d") is not None for _ in range(32)]
    twin_seq = [twin.fire("c.d") is not None for _ in range(32)]
    assert seq == twin_seq and True in seq and False in seq
    # default `at` for torn faults is seeded from ctx size.
    p2 = chaos.ChaosPlan("seed=3;t.x")
    inj = p2.fire("t.x", size=50)
    assert 1 <= inj["at"] < 50
    with pytest.raises(ValueError):
        chaos.ChaosPlan("bad clause@@")


def test_chaos_install_same_spec_keeps_the_live_plan():
    """Re-installing the SAME spec is a no-op (fire counters survive):
    the fleet installs once, then each per-device pool's constructor
    installs the identical spec — a reset mid-construction would lose
    counts a pool replay already fired."""
    plan = chaos.install("seed=1;a.b@n=2")
    assert plan.fire("a.b") is None  # invocation 1 of 2
    assert chaos.install("seed=1;a.b@n=2") is plan
    assert plan.fire("a.b") == {}  # invocation 2 still fires
    # A DIFFERENT spec replaces the plan; None clears it.
    assert chaos.install("seed=1;a.b@n=3") is not plan
    assert chaos.install(None) is None


def test_chaos_supervise_wedge_verdict(tmp_path):
    """A scripted wedge verdict kills the worker group and classifies as
    wedged — the breaker/quarantine evidence path, no SIGSTOP needed."""
    import sys

    from stateright_tpu.supervise import run_worker

    chaos.install("supervise.wedge@n=2")
    res = run_worker(
        [sys.executable, "-c", "import time; time.sleep(60)"],
        poll_s=0.1,
        timeout_s=30.0,
    )
    assert res.killed == "chaos: simulated wedge verdict"
    assert res.wedged and not res.crashed
    assert res.seconds < 10.0


def test_chaos_checkpoint_torn_falls_back_a_rotation(tmp_path):
    """checkpoint.torn tears the live rotation at byte K after the
    atomic replace; latest_valid_checkpoint skips it (typed) and lands
    on the previous rotation — the designed fallback, now scriptable."""
    from stateright_tpu.checkpoint import (
        CheckpointCorrupt,
        latest_valid_checkpoint,
        load_checkpoint,
    )
    from stateright_tpu.models.two_phase_commit import PackedTwoPhaseSys

    ck = (
        PackedTwoPhaseSys(3)
        .checker()
        .spawn_xla(frontier_capacity=1 << 9, table_capacity=1 << 12)
    )
    ck.join()
    path = str(tmp_path / "ck.npz")
    ck.save_checkpoint(path, keep=2)
    chaos.install("checkpoint.torn@n=1:at=40")
    ck.save_checkpoint(path, keep=2)  # live file torn, .1 intact
    chaos.install(None)
    with pytest.raises(CheckpointCorrupt):
        load_checkpoint(path)
    assert latest_valid_checkpoint(path) == path + ".1"


def test_chaos_lint_timeout_fails_open(tmp_path):
    """lint.timeout simulates the admission-lint subprocess timing out:
    the job admits fail-open with ok=None and lint_errors counted — the
    blind-gate path, scriptable without a 240s wait."""
    svc = CheckerService(_config(
        tmp_path, max_inflight=0, admission_lint=True,
        chaos="lint.timeout@n=1",
    ))
    try:
        job = svc.submit("2pc:3")
        assert job.lint["ok"] is None
        assert any("TimeoutExpired" in e for e in job.lint["errors"])
        assert svc.gauges()["lint_errors"] == 1
    finally:
        svc.close()


def test_chaos_worker_points_map_to_job_flags(tmp_path):
    """worker.die/worker.freeze fire per SUBMIT (@n counts admissions)
    and land as the matching job-level chaos flags with the exactly-once
    marker armed by default."""
    svc = CheckerService(_config(
        tmp_path, max_inflight=0,
        chaos="worker.die@n=2:depth=5;worker.freeze@n=1:depth=4,once=0",
    ))
    try:
        first = svc.submit("2pc:3")
        second = svc.submit("2pc:3")
        assert first.chaos == {"freeze_at_depth": 4}  # once=0: no marker
        assert second.chaos["die_at_depth"] == 5
        assert second.chaos["marker"].startswith(second.dir)
    finally:
        svc.close()


# --- restart recovery (journal-driven; no workers) --------------------------


def _disarmed(tmp_path, **kw):
    """A service whose scheduler can never start a worker
    (max_inflight=0): admission + journal + recovery accounting only."""
    return CheckerService(_config(tmp_path, max_inflight=0, **kw))


def test_recovery_restores_done_jobs_and_idempotency(tmp_path):
    svc = _disarmed(tmp_path)
    job = svc.submit("2pc:3", idempotency_key="alpha", max_seconds=60.0)
    # Settle it as done the way the service would (under the lock).
    with svc._cond:
        job.status = "done"
        job.completed_unix_ts = time.time()
        job.result = {"generated": 1146, "unique": 288, "max_depth": 11,
                      "seconds": 1.0}
        svc._counters.inc("jobs_done")
        svc._jlog("completed", job=job.id, status="done", error=None,
                  result=job.result)
    svc.close()

    svc2 = _disarmed(tmp_path)
    try:
        rec = svc2.gauges()["journal"]["recovery"]
        assert rec["records_replayed"] >= 3 and rec["torn"] is None
        restored = svc2.job(job.id)
        assert restored.status == "done" and restored.recovered
        assert restored.result["generated"] == 1146
        # Idempotent resubmission after restart: the SAME job comes back,
        # nothing is re-run, the dedupe is counted.
        again = svc2.submit("2pc:3", idempotency_key="alpha")
        assert again is restored
        assert svc2.gauges()["idem_dedups"] == 1
        assert svc2.gauges()["jobs_recovered"] == 1
    finally:
        svc2.close()


def test_qos_scheduler_state_replays(tmp_path):
    """Kill -9 + restart restores the QoS scheduler exactly (ISSUE 18):
    queued jobs keep tenant/priority/deadline, the per-class fair-share
    strides fold from replayed ``started`` events, tenant quotas re-arm
    over the restored queue, and the drain-rate window reseeds from
    journaled completion timestamps so the first post-restart
    Retry-After is measured, not cold."""
    svc = _disarmed(tmp_path, tenant_max_queued=2)
    vip = svc.submit("2pc:3", tenant="t1", priority="interactive",
                     deadline_s=90.0)
    svc.submit("2pc:3", tenant="t1")  # t1's queued quota now full
    done = svc.submit("2pc:3", tenant="t2", priority="best_effort")
    with svc._cond:
        done.status = "running"
        svc._jlog("started", job=done.id, attempt=0, engine="xla",
                  resumed_from=None, pid=None)
        done.status = "done"
        done.completed_unix_ts = time.time()
        done.result = {"generated": 1146, "unique": 288, "max_depth": 11,
                       "seconds": 1.0}
        svc._counters.inc("jobs_done")
        svc._jlog("completed", job=done.id, status="done", error=None,
                  result=done.result)
    svc.close()

    svc2 = _disarmed(tmp_path, tenant_max_queued=2)
    try:
        restored = svc2.job(vip.id)
        assert restored.status == "queued"
        assert restored.priority == "interactive"
        assert restored.tenant == "t1"
        assert restored.deadline_s == 90.0
        # Per-class stride state folded from the replayed `started`.
        assert svc2._qos_served.get("best_effort") == 1
        # The tenant quota re-arms over the RESTORED queue.
        with pytest.raises(AdmissionError) as exc:
            svc2.submit("2pc:3", tenant="t1")
        assert "queued quota reached" in exc.value.reason
        assert svc2.gauges()["quota_rejects"] == 1
        # Drain window reseeded from the journaled completion.
        assert len(svc2._drain) == 1
        assert svc2._drain[0][1] == "best_effort"
    finally:
        svc2.close()


def test_recovery_requeues_inflight_and_charges_budget(tmp_path):
    """An in-flight job requeues on restart with the wall-clock it had
    already spent charged (journal last-ts bounds 'alive until here')."""
    svc = _disarmed(tmp_path)
    job = svc.submit("2pc:3", idempotency_key="b", max_seconds=500.0)
    with svc._cond:
        job.status = "running"
        svc._jlog("started", job=job.id, attempt=0, engine="xla",
                  resumed_from=None, pid=None)
        time.sleep(1.1)
        svc._jlog("breaker_closed")  # any later record advances last_ts
    svc.close()

    svc2 = _disarmed(tmp_path)
    try:
        restored = svc2.job(job.id)
        assert restored.status == "queued"
        assert restored.consumed_s >= 1.0
        rec = svc2.gauges()["journal"]["recovery"]
        assert rec["jobs_requeued"] == 1
    finally:
        svc2.close()


def test_recovery_expired_budget_fails_typed_not_rerun(tmp_path):
    """A job whose budget was already spent when the pool died must fail
    typed at recovery — never burn a fresh budget re-running."""
    svc = _disarmed(tmp_path)
    job = svc.submit("2pc:3", idempotency_key="c", max_seconds=0.5)
    with svc._cond:
        job.status = "running"
        svc._jlog("started", job=job.id, attempt=0, engine="xla",
                  resumed_from=None, pid=None)
        time.sleep(1.1)
        svc._jlog("breaker_closed")
    svc.close()

    svc2 = _disarmed(tmp_path)
    try:
        restored = svc2.job(job.id)
        assert restored.status == "failed"
        assert "budget exhausted" in restored.error
        assert "before the restart" in restored.error
        assert restored.attempts == []  # never re-run
        # The typed failure is itself journaled: a THIRD incarnation
        # restores it terminal without reconsidering.
        svc2.close()
        svc3 = _disarmed(tmp_path)
        assert svc3.job(job.id).status == "failed"
        assert svc3.job(job.id).attempts == []
        svc3.close()
    except BaseException:
        svc2.close()
        raise


def test_recovery_torn_tail_replays_prefix_and_amputates(tmp_path):
    """Service-level torn-tail recovery: truncate the live journal at a
    random byte inside the LAST record; the restart replays everything
    before it, reports the torn tail, and recompacts so the journal is
    clean again."""
    svc = _disarmed(tmp_path)
    svc.submit("2pc:3", idempotency_key="t1", max_seconds=60.0)
    svc.submit("2pc:3", idempotency_key="t2", max_seconds=60.0)
    svc.close()
    jpath = os.path.join(svc._cfg.run_dir, "journal.jsonl")
    data = open(jpath, "rb").read()
    last_line_start = data[:-1].rfind(b"\n") + 1
    cut = random.Random(7).randint(last_line_start + 1, len(data) - 2)
    with open(jpath, "wb") as fh:
        fh.write(data[:cut])

    svc2 = _disarmed(tmp_path)
    try:
        rec = svc2.gauges()["journal"]["recovery"]
        assert rec["torn"] is not None
        # Job t1 replayed fully; t2's admitted event was the torn record
        # or survived — either way the clean prefix restored exactly.
        assert "job-0001" in {j.id for j in svc2.jobs()}
        # Recompaction amputated the torn bytes: the live journal reads
        # clean end to end now.
        assert read_journal(jpath).torn is None
    finally:
        svc2.close()


def test_recovery_restores_open_breaker_and_reprobes_now(tmp_path):
    """A restart must not forget an open breaker — and the restored-open
    breaker re-probes IMMEDIATELY (not an interval later), so the first
    job after a restart never goes straight at a wedged device."""
    import sys

    svc = _disarmed(tmp_path)
    with svc._cond:
        svc._breaker = "open"
        svc._breaker_opened_unix_ts = time.time()
        svc._consecutive_wedges = 3
        svc._jlog("breaker_tripped", consecutive=3)
    svc.close()

    # probe_auto on, instant-success probe, LONG interval: only the
    # immediate restart probe can close it within the poll window.
    svc2 = CheckerService(_config(
        tmp_path, max_inflight=0, probe_auto=True,
        probe_interval_s=3600.0,
        probe_argv=[sys.executable, "-c", "pass"],
    ))
    try:
        deadline = time.monotonic() + 30.0
        while svc2.degraded and time.monotonic() < deadline:
            time.sleep(0.1)
        assert not svc2.degraded
        g = svc2.gauges()
        assert g["breaker_closes"] == 1 and g["device_probes"] == 1
        # The close is journaled: a further restart stays closed.
    finally:
        svc2.close()
    svc3 = _disarmed(tmp_path, probe_auto=False)
    assert svc3.gauges()["breaker"]["state"] == "closed"
    svc3.close()


def test_artifact_sweep_reclaims_complete_jobs(tmp_path):
    """Journal-complete jobs' run-dir artifacts are swept past the
    retention; the pool gauge records it."""
    svc = _disarmed(tmp_path, artifact_retention_s=0.0)
    job = svc.submit("2pc:3", idempotency_key="s1", max_seconds=60.0)
    for name in ("hb.json", "trace.jsonl", "ck.npz", "worker0.out"):
        with open(os.path.join(job.dir, name), "w") as fh:
            fh.write("x")
    with svc._cond:
        job.status = "done"
        job.completed_unix_ts = time.time() - 10.0
        job.result = {"generated": 1, "unique": 1}
        svc._jlog("completed", job=job.id, status="done", error=None,
                  result=job.result)
        svc._sweep_artifacts()
    assert not os.path.isdir(job.dir)
    assert svc.gauges()["artifacts_swept"] == 1
    # Sweeping is idempotent and the journal survives it.
    with svc._cond:
        svc._sweep_artifacts()
    assert svc.gauges()["artifacts_swept"] == 1
    svc.close()
    svc2 = _disarmed(tmp_path)
    assert svc2.job(job.id).status == "done"
    svc2.close()


# --- fleet durability (ISSUE 15): routing journal + restart replay ----------


def test_fleet_replay_folds_routes_and_migrations():
    records = []

    def rec(event, **kw):
        r = {"v": 1, "seq": len(records) + 1, "event": event, **kw}
        records.append(r)
        return r

    rec("routed", ts=1.0, job="fjob-0001", spec="2pc:3", device=0,
        pool_job="job-0001", idempotency_key="k1",
        tenant="t9", priority="interactive", deadline_s=120.0)
    rec("routed", ts=1.5, job="fjob-0002", spec="abd:2", device=1,
        pool_job="job-0001", idempotency_key=None)
    rec("migrated", ts=2.0, job="fjob-0001", from_device=0, to_device=1,
        pool_job="job-0002", reason="device-0 lost")
    rec("quiesced", ts=2.5, device=2, reason="idle")
    rec("quiesced", ts=2.6, device=1, reason="idle")
    rec("woken", ts=3.0, device=1, reason="pressure")
    state = _fleet_replay(records)
    assert state["next_id"] == 2
    assert state["routes"]["fjob-0001"] == {
        "device": 1, "pool_job": "job-0002", "spec": "2pc:3",
        "idempotency_key": "k1", "trace_id": None,
        "tenant": "t9", "priority": "interactive", "deadline_s": 120.0,
    }
    assert state["routes"]["fjob-0002"]["device"] == 1
    # A pre-QoS record (no tenant/priority) folds to the defaults.
    assert state["routes"]["fjob-0002"]["tenant"] == "default"
    assert state["routes"]["fjob-0002"]["priority"] == "batch"
    assert state["idem"] == {"k1": "fjob-0001"}
    assert state["migrations"] == {"fjob-0001": 1}
    assert state["counters"]["routed"] == 2
    assert state["counters"]["migrations"] == 1
    assert state["order"] == ["fjob-0001", "fjob-0002"]
    # Elastic events fold to the live quiesced set + counters.
    assert state["quiesced"] == {2}
    assert state["counters"]["pools_quiesced"] == 2
    assert state["counters"]["pools_woken"] == 1


def _fleet_disarmed(tmp_path, devices=3):
    return FleetService(FleetConfig(
        run_dir=str(tmp_path / "fleet"),
        devices=devices,
        monitor_interval_s=0.3,
        pool=_config(tmp_path, max_inflight=0),
    ))


def test_fleet_restart_replays_routing(tmp_path):
    """Constructing a fleet over a run dir with journals restores the
    SAME fleet-job -> (device, pool job) placement: every pool replays
    its own journal, then fleet.jsonl re-attaches the routing — and
    idempotent resubmission returns the restored FleetJob."""
    f1 = _fleet_disarmed(tmp_path)
    a = f1.submit("2pc:3", idempotency_key="fa")
    b = f1.submit("2pc:4", idempotency_key="fb")
    c = f1.submit("abd:2", idempotency_key="fc")
    routes1 = {j.id: (j.device, j.pool_job.id) for j in f1.jobs()}
    assert len({d for d, _ in routes1.values()}) == 3  # spread
    f1.close()

    f2 = _fleet_disarmed(tmp_path)
    try:
        routes2 = {j.id: (j.device, j.pool_job.id) for j in f2.jobs()}
        assert routes1 == routes2
        assert all(j.recovered for j in f2.jobs())
        rec = f2.gauges()["journal"]["recovery"]
        assert rec["torn"] is None
        assert rec["routes_recovered"] == 3 and rec["attached"] == 3
        # Pool-side: the jobs requeued through each pool's own journal.
        assert all(j.pool_job.status == "queued" for j in f2.jobs())
        # Fleet-scoped idempotency survives the restart.
        again = f2.submit("2pc:3", idempotency_key="fa")
        assert again is f2.job(a.id)
        assert f2.gauges()["idem_dedups"] == 1
    finally:
        f2.close()


def test_fleet_restart_adopts_pool_jobs_lost_from_torn_fleet_tail(tmp_path):
    """A torn fleet.jsonl tail loses a routing record, but the POOL
    journal still owns the job: the restart adopts it back by
    idempotency key instead of double-running on resubmission."""
    f1 = _fleet_disarmed(tmp_path)
    f1.submit("2pc:3", idempotency_key="ta")
    f1.submit("abd:2", idempotency_key="tb")
    f1.close()
    fpath = os.path.join(str(tmp_path / "fleet"), "fleet.jsonl")
    data = open(fpath, "rb").read()
    # Amputate the LAST routed record entirely (a boundary-cut torn
    # tail: the fleet never journaled tb's route, the pool did).
    cut = data[:-1].rfind(b"\n") + 1
    with open(fpath, "wb") as fh:
        fh.write(data[:cut])

    f2 = _fleet_disarmed(tmp_path)
    try:
        assert f2.gauges()["journal"]["recovery"]["routes_recovered"] >= 1
        # tb was adopted from its pool's journal; resubmitting it dedupes
        # to the adopted job — nothing double-runs.
        jobs_before = len(f2.jobs())
        again = f2.submit("abd:2", idempotency_key="tb")
        assert len(f2.jobs()) == jobs_before
        assert again.pool_job.idempotency_key == "tb"
        assert f2.gauges()["idem_dedups"] == 1
    finally:
        f2.close()


def test_fleet_restart_reroutes_orphans_from_journaled_spec(tmp_path):
    """A restart that cannot re-attach a routed pool job (the pool's
    journal is gone) leaves an ORPHAN — the repair pass re-routes it to
    a healthy sibling from the fleet-journaled spec instead of letting
    waiters poll forever; with no spec either, it fails typed. The spec
    survives _recover's compaction, so even a SECOND crash before the
    repair pass runs stays recoverable."""
    f1 = _fleet_disarmed(tmp_path, devices=2)
    a = f1.submit("2pc:3", idempotency_key="oa")
    victim = a.device
    f1.close()
    os.remove(os.path.join(
        str(tmp_path / "fleet"), f"device-{victim}", "journal.jsonl"
    ))

    def reopen():  # slow monitor: the repair pass is driven by hand
        return FleetService(FleetConfig(
            run_dir=str(tmp_path / "fleet"),
            devices=2,
            monitor_interval_s=60.0,
            pool=_config(tmp_path, max_inflight=0),
        ))

    f2 = reopen()
    try:
        assert f2.job(a.id).pool_job is None
        assert f2.gauges()["journal"]["recovery"]["orphaned"] == 1
    finally:
        # Die again before the repair pass ran (the recovery already
        # compacted fleet.jsonl — the orphan's spec must have survived).
        f2.close()

    f3 = reopen()
    try:
        fjob = f3.job(a.id)
        assert fjob.pool_job is None
        moved = f3._migrate_stragglers()
        assert moved == 1
        assert fjob.pool_job is not None and fjob.pool_job.spec == "2pc:3"
        # The journal-less device is healthy (only its HISTORY died), so
        # any healthy pool — the victim included — is a valid target.
        assert fjob.device is not None
        assert len(fjob.migrations) >= 1
        assert f3.gauges()["migrations"] >= 1
        # The unrecoverable shape (no journaled spec at all) settles
        # typed instead of hanging its waiters.
        fjob._orphan_spec = None
        fjob.pool_job = None
        f3._migrate_stragglers()
        assert fjob.done and "unrecoverable" in fjob.error
        assert fjob.wait(timeout=1.0)
        # An orphan whose journaled spec no longer parses (e.g. a user
        # family not registered in this incarnation) also fails typed —
        # a retry would throw identically, and the ValueError must not
        # kill the monitor sweep and stall every other migration.
        fjob._rejected = None
        fjob._orphan_spec = "not-a-registered-spec"
        f3._migrate_stragglers()
        assert fjob.done and "migration failed" in fjob.error
    finally:
        f3.close()


# --- distributed-trace continuity (docs/observability.md) -------------------


def test_trace_id_minted_journaled_and_restored(tmp_path):
    """Every submission mints a trace id — tracer on or off — and the
    journal carries it ('submitted'/'started'): a restart restores the
    SAME id, so spans from pre- and post-crash attempts stitch into one
    trace; idempotent resubmission keeps it too."""
    svc = _disarmed(tmp_path)
    job = svc.submit("2pc:3", idempotency_key="t1", max_seconds=120.0)
    tid = job.trace_id
    assert tid and len(tid) == 16
    assert job.snapshot()["trace_id"] == tid
    with svc._cond:
        job.status = "running"
        svc._jlog("started", job=job.id, attempt=0, engine="xla",
                  resumed_from=None, pid=None, trace_id=job.trace_id)
    svc.close()

    svc2 = _disarmed(tmp_path)
    try:
        assert svc2.job(job.id).trace_id == tid
        again = svc2.submit("2pc:3", idempotency_key="t1")
        assert again.trace_id == tid
    finally:
        svc2.close()


def test_replay_state_folds_trace_id():
    """'submitted' carries the trace id; a later 'started' (a migration
    resubmit journals it there too) refreshes it; journals from before
    the tracing round replay with trace_id None, not a KeyError."""
    records = []

    def rec(event, **kw):
        r = {"v": 1, "seq": len(records) + 1, "event": event, **kw}
        records.append(r)
        return r

    rec("submitted", ts=1.0, job="job-0001", spec="2pc:3",
        max_seconds=60.0, dir="s/job-0001", trace_id="aa" * 8)
    rec("submitted", ts=1.5, job="job-0002", spec="2pc:3",
        max_seconds=60.0, dir="s/job-0002")  # pre-tracing record shape
    rec("started", ts=2.0, job="job-0002", attempt=0, engine="xla",
        pid=999, trace_id="bb" * 8)
    state = _replay_state(records)
    assert state["jobs"]["job-0001"]["trace_id"] == "aa" * 8
    assert state["jobs"]["job-0002"]["trace_id"] == "bb" * 8


def test_fleet_trace_id_spans_routing_and_restart(tmp_path):
    """The fleet mints the trace id; the routed pool job JOINS it (one
    id across the fleet→pool hop), fleet.jsonl journals it, and a
    full-fleet restart restores it on both tiers."""
    f1 = _fleet_disarmed(tmp_path)
    a = f1.submit("2pc:3", idempotency_key="ft")
    tid = a.trace_id
    assert tid and a.pool_job.trace_id == tid
    assert a.snapshot()["trace_id"] == tid
    f1.close()

    f2 = _fleet_disarmed(tmp_path)
    try:
        fjob = f2.job(a.id)
        assert fjob.trace_id == tid
        assert fjob.pool_job.trace_id == tid
    finally:
        f2.close()


def test_fleet_migration_keeps_trace_id(tmp_path):
    """A migrated job's new attempt on the sibling device continues the
    ORIGINAL trace: the straggler repair resubmits with the journaled
    trace id, so the post-migration spans stitch to the pre-loss ones."""

    def reopen(interval):
        return FleetService(FleetConfig(
            run_dir=str(tmp_path / "fleet"),
            devices=2,
            monitor_interval_s=interval,
            pool=_config(tmp_path, max_inflight=0),
        ))

    f1 = reopen(60.0)
    a = f1.submit("2pc:3", idempotency_key="mt")
    tid = a.trace_id
    victim = a.device
    f1.close()
    os.remove(os.path.join(
        str(tmp_path / "fleet"), f"device-{victim}", "journal.jsonl"
    ))

    f2 = reopen(60.0)
    try:
        fjob = f2.job(a.id)
        assert fjob.trace_id == tid  # restored from fleet.jsonl's route
        assert f2._migrate_stragglers() == 1
        assert fjob.pool_job is not None
        assert fjob.pool_job.trace_id == tid
    finally:
        f2.close()


def test_fleet_pools_export_chaos_to_workers(tmp_path):
    """FleetConfig(chaos=) reaches worker processes like a single pool's
    does: the spec forwards into every pool config (the _worker_env
    STPU_CHAOS export keys on it) without resetting the fleet's
    installed plan."""
    import types

    spec = "seed=5;checkpoint.torn@n=1"
    fleet = FleetService(FleetConfig(
        run_dir=str(tmp_path / "fleet"),
        devices=2,
        pool=_config(tmp_path, max_inflight=0),
        chaos=spec,
    ))
    try:
        live = chaos.plan()
        assert live is not None and live.spec == spec
        assert all(p._cfg.chaos == spec for p in fleet.pools)
        env = fleet.pools[0]._worker_env(
            types.SimpleNamespace(trace_path="unused"), device=False
        )
        assert env["STPU_CHAOS"] == spec
    finally:
        fleet.close()


@pytest.mark.slow
def test_fleet_sigkill_restart_replay_converges(chaos_reference):
    """ISSUE 15 acceptance: SIGKILL the WHOLE 3-device fleet at a seeded
    point mid-schedule, restart over the same run dir — the fleet journal
    replays routing, each pool replays its jobs, and every job completes
    exactly once with counts bit-identical to the undisturbed baseline."""
    sc, base, schedule, ref = chaos_reference
    rep = sc.run_scenario(
        "kill", 42, schedule, os.path.join(base, "fleet3"),
        reference=ref, max_inflight=2, fleet=3,
    )
    assert rep["ok"], rep["problems"]
    assert rep["restarts"] >= 1 or rep["faults"]["kill_after_s"] > rep["elapsed_s"]
    assert rep["fleet"]["devices"] == 3
    assert rep["turnaround_s"]["n"] == 3


@pytest.mark.slow
def test_fleet_device_lost_mid_schedule_converges(chaos_reference):
    """ISSUE 15 acceptance: a seeded device.lost kills one device's pool
    mid-schedule; its jobs migrate and the fleet converges exactly-once,
    bit-identical — with the migration PROVEN in the SLO line."""
    sc, base, schedule, ref = chaos_reference
    rep = sc.run_scenario(
        "device_lost", 42, schedule, os.path.join(base, "fleet_lost"),
        reference=ref, max_inflight=2, fleet=2,
    )
    assert rep["ok"], rep["problems"]
    assert rep["fleet"]["migrations"] >= 1


# --- restart drills (the real service, killed for real) ---------------------


def _drill_schedule(idem, specs=("2pc:3",)):
    return {
        "jobs": [
            {"idem": f"{idem}-{i}", "spec": spec, "delay_s": 0.2 * i,
             "max_seconds": 240.0}
            for i, spec in enumerate(specs)
        ]
    }


def test_smoke_service_restart_resume(tmp_path):
    """The <30s tier-0 restart drill (tools/smoke.sh): the service
    SIGKILLs itself right after journaling `started` (deterministic:
    journal.die@n=3), the restart replays the journal, kills the
    orphaned worker, requeues, and the job completes exactly once with
    exact pinned counts."""
    sc = _harness()
    run_dir = str(tmp_path / "drill")
    os.makedirs(run_dir)
    schedule = _drill_schedule("drill")
    sp = os.path.join(run_dir, "schedule.json")
    with open(sp, "w") as fh:
        json.dump(schedule, fh)
    rc = sc.run_incarnation(
        run_dir, sp, chaos="seed=1;journal.die@n=3", wait_s=120.0
    )
    assert rc == -9  # died by its own injected SIGKILL
    rc = sc.run_incarnation(run_dir, sp, wait_s=120.0)
    assert rc == 0
    inv = sc.check_invariant(run_dir, schedule, None)
    assert inv["ok"], inv["problems"]
    with open(os.path.join(run_dir, "driver_results.json")) as fh:
        results = json.load(fh)["jobs"]
    got = results["drill-0"]
    assert got["status"] == "done"
    assert (got["result"]["generated"], got["result"]["unique"]) == PINNED_2PC3
    slo = sc.slo_stats(run_dir)
    assert slo["journal"]["records_replayed"] == 3
    assert slo["journal"]["jobs_requeued"] == 1
    # The orphaned first worker was killed by journaled pid before the
    # job was rescheduled (exactly-once depends on it).
    assert slo["journal"]["orphans_killed"] in (0, 1)


@pytest.fixture(scope="module")
def chaos_reference(tmp_path_factory):
    """One undisturbed baseline run of the seeded 3-job schedule — the
    ground truth both convergence pins compare against bit-for-bit."""
    sc = _harness()
    base = str(tmp_path_factory.mktemp("chaos"))
    schedule = sc.build_schedule(42, 3, 240.0)
    rep = sc.run_scenario("baseline", 42, schedule, base, reference=None)
    assert rep["ok"], rep["problems"]
    ref = sc.reference_counts(os.path.join(base, "baseline"), schedule)
    return sc, base, schedule, ref


@pytest.mark.slow
def test_chaos_pin_service_sigkill_converges(chaos_reference):
    """ISSUE 12 acceptance: SIGKILL the CheckerService process at a
    seeded random point of a 3-concurrent-job schedule, restart from the
    same run dir — every job completes exactly once, counts bit-identical
    to the undisturbed run."""
    sc, base, schedule, ref = chaos_reference
    rep = sc.run_scenario(
        "kill", 42, schedule, base, reference=ref, max_inflight=2
    )
    assert rep["ok"], rep["problems"]
    assert rep["turnaround_s"]["n"] == 3


@pytest.mark.slow
def test_chaos_pin_torn_journal_converges(chaos_reference):
    """Same schedule with journal-append torn-tail injection: the crash
    lands MID-append, the restart recovers the typed torn tail and still
    converges exactly-once, bit-identical."""
    sc, base, schedule, ref = chaos_reference
    rep = sc.run_scenario(
        "torn", 42, schedule, base, reference=ref, max_inflight=2
    )
    assert rep["ok"], rep["problems"]
    assert rep["journal"]["torn"] is not None  # the tear really landed
