"""STPU_EXPAND_LAYOUT=planes: the expand vmap emits [A, W, F] planes
directly (no (8,128)-padded [F, A, W] intermediate) — attack 2 of the
BASELINE roadmap, opt-in for chip A/Bs.

The layouts must be bit-identical in semantics: same counts, same
winner election, same discoveries. "rows" stays the default because a
transpose fused into a vmapped kernel is the shape XLA:CPU (jax 0.9.0)
miscompiled in round 3b — these tests are the canary: if a jax upgrade
or model kernel change trips that bug again, the exact counts break
here, on CPU, before any chip run trusts the knob.
"""

import pytest

from stateright_tpu.models.increment_lock import PackedIncrementLock
from stateright_tpu.models.paxos import PackedPaxos
from stateright_tpu.models.two_phase_commit import PackedTwoPhaseSys


def _run(model, **kw):
    checker = model.checker().spawn_xla(**kw)
    while not checker.is_done():
        checker._run_block()
    return checker


@pytest.mark.parametrize(
    "name,build,kw,pinned",
    [
        (
            "2pc rm=4",
            lambda: PackedTwoPhaseSys(4),
            dict(frontier_capacity=1 << 12, table_capacity=1 << 14, dedup="sorted"),
            (8_258, 1_568),
        ),
        (
            "paxos 2c/3s",
            lambda: PackedPaxos(2, 3),
            dict(frontier_capacity=1 << 12, table_capacity=1 << 16, dedup="sorted"),
            (32_971, 16_668),
        ),
        (
            "increment_lock 3t",
            lambda: PackedIncrementLock(3),
            dict(frontier_capacity=1 << 10, table_capacity=1 << 13, dedup="sorted"),
            (61, 61),
        ),
    ],
)
def test_planes_expand_layout_exact_counts(monkeypatch, name, build, kw, pinned):
    monkeypatch.setenv("STPU_EXPAND_LAYOUT", "planes")
    checker = _run(build(), **kw)
    assert (checker.state_count(), checker.unique_state_count()) == pinned, name


def test_bad_layout_rejected(monkeypatch):
    monkeypatch.setenv("STPU_EXPAND_LAYOUT", "diagonal")
    with pytest.raises(ValueError, match="STPU_EXPAND_LAYOUT"):
        PackedTwoPhaseSys(3).checker().spawn_xla(
            frontier_capacity=1 << 10, table_capacity=1 << 12
        )
