"""tools/bench_regress.py — the perf-regression gate (ISSUE 13): typed
verdicts against the archived BENCH_r*.json trajectory. No jax, no
device — pure JSON in, one verdict line out. Pins the acceptance
criterion's three behaviors (pass on real lines, fail on a synthetically
degraded line, "no baseline" as a typed non-failure on an empty dir)
plus the honest skips (resumed lines, missing platforms, missing chaos
artifact) and the exit-code contract.
"""

import importlib.util
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOL = os.path.join(REPO, "tools", "bench_regress.py")

_spec = importlib.util.spec_from_file_location("bench_regress", TOOL)
br = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(br)


def _archive(tmp_path, lines):
    d = tmp_path / "archive"
    d.mkdir(exist_ok=True)
    for i, line in enumerate(lines, 1):
        # The driver's wrapper shape ({"n", "cmd", "parsed": line}).
        (d / f"BENCH_r{i:02d}.json").write_text(
            json.dumps({"n": i, "parsed": line})
        )
    return str(d)


CPU_LINES = [
    {"metric": "2pc(rm=6) generated states/sec, spawn_xla, cpu",
     "value": 129014.5, "unit": "states/sec"},
    {"metric": "2pc(rm=7) generated states/sec, spawn_xla, cpu",
     "value": 600075.9, "unit": "states/sec"},
    {"metric": "2pc(rm=7) generated states/sec, spawn_xla, cpu",
     "value": 771620.7, "unit": "states/sec", "count_ok": True},
]


def _fresh(value, **kw):
    line = {"metric": "2pc(rm=7) generated states/sec, spawn_xla, cpu",
            "value": value, "count_ok": True}
    line.update(kw)
    return br.normalize_fresh(line)


def test_trajectory_loading(tmp_path):
    arch = _archive(tmp_path, CPU_LINES)
    traj = br.load_trajectory(arch)
    assert set(traj) == {"cpu"}
    assert traj["cpu"]["best"] == 771620.7
    assert traj["cpu"]["lines"] == 3
    # Garbage files are skipped, not fatal.
    (tmp_path / "archive" / "BENCH_r99.json").write_text("{torn")
    assert br.load_trajectory(arch)["cpu"]["lines"] == 3


def test_pass_on_real_trajectory(tmp_path):
    traj = br.load_trajectory(_archive(tmp_path, CPU_LINES))
    line = br.judge(_fresh(760_000.0), traj, None)
    assert line["verdict"] == "pass"
    by_name = {c["name"]: c for c in line["checks"]}
    assert by_name["throughput"]["verdict"] == "pass"
    assert by_name["count_ok"]["verdict"] == "pass"
    assert by_name["slo"]["verdict"] == "skip"  # no chaos artifact


def test_fail_on_degraded_line(tmp_path):
    traj = br.load_trajectory(_archive(tmp_path, CPU_LINES))
    line = br.judge(_fresh(100_000.0), traj, None)
    assert line["verdict"] == "fail"
    tp = [c for c in line["checks"] if c["name"] == "throughput"][0]
    assert tp["verdict"] == "fail"
    assert tp["baseline"] == 771620.7
    # count_ok / lint_ok are independent failure axes.
    assert br.judge(_fresh(760_000.0, count_ok=False), traj, None)["verdict"] == "fail"
    assert br.judge(_fresh(760_000.0, lint_ok=False), traj, None)["verdict"] == "fail"


def test_no_baseline_is_typed_nonfailure(tmp_path):
    empty = tmp_path / "empty_archive"
    empty.mkdir()
    line = br.judge(_fresh(1.0), br.load_trajectory(str(empty)), None)
    assert line["verdict"] == "no_baseline"
    # ... but a missing archive only excuses the throughput comparison:
    # an exact-count or lint violation still FAILS the gate.
    assert br.judge(
        _fresh(1.0, count_ok=False), br.load_trajectory(str(empty)), None
    )["verdict"] == "fail"
    assert br.judge(
        _fresh(1.0, lint_ok=False), br.load_trajectory(str(empty)), None
    )["verdict"] == "fail"


def test_honest_skips(tmp_path):
    traj = br.load_trajectory(_archive(tmp_path, CPU_LINES))
    # A resumed line measures a checkpoint tail, not a cold pass: the
    # throughput check skips instead of judging it, and a slow resumed
    # line therefore cannot fail the gate.
    line = br.judge(_fresh(5_000.0, resumed="measured"), traj, None)
    tp = [c for c in line["checks"] if c["name"] == "throughput"][0]
    assert tp["verdict"] == "skip"
    assert line["verdict"] == "pass"
    # A line whose fleet provenance records cross-device migrations was
    # measured amid failover evacuations: skip, not fail — and a
    # migration-free fleet line stays judged normally.
    line = br.judge(
        _fresh(5_000.0, fleet={"devices": 2, "migrations": 3}), traj, None
    )
    tp = [c for c in line["checks"] if c["name"] == "throughput"][0]
    assert tp["verdict"] == "skip"
    assert "migration" in tp["detail"]
    assert line["verdict"] == "pass"
    line = br.judge(
        _fresh(100_000.0, fleet={"devices": 2, "migrations": 0}), traj, None
    )
    assert [c for c in line["checks"] if c["name"] == "throughput"][0][
        "verdict"] == "fail"
    # A platform with no archived line yet: skip, not fail (banking the
    # first chip line STARTS that trajectory).
    tpu = br.normalize_fresh(
        {"metric": "2pc(rm=8) generated states/sec, spawn_xla, tpu",
         "value": 2.0e6, "count_ok": True}
    )
    line = br.judge(tpu, traj, None)
    assert line["verdict"] == "pass"
    assert [c for c in line["checks"] if c["name"] == "throughput"][0][
        "verdict"] == "skip"


def test_chaos_slo_checks(tmp_path):
    traj = br.load_trajectory(_archive(tmp_path, CPU_LINES))
    good = {
        "ok": True,
        "scenarios": {"baseline": {
            "admission_latency_ms": {"p50": 3.0, "p99": 40.0},
            "turnaround_s": {"p50": 9.0, "p99": 30.0},
        }},
    }
    line = br.judge(_fresh(760_000.0), traj, good)
    assert line["verdict"] == "pass"
    assert [c for c in line["checks"] if c["name"] == "slo"][0]["verdict"] == "pass"
    # p99 above the limit fails; a failed sweep fails outright.
    slow = {"ok": True, "scenarios": {"baseline": {
        "admission_latency_ms": {"p99": 99_000.0},
        "turnaround_s": {"p99": 10.0},
    }}}
    assert br.judge(_fresh(760_000.0), traj, slow)["verdict"] == "fail"
    assert br.judge(
        _fresh(760_000.0), traj, {"ok": False, "scenarios": {}}
    )["verdict"] == "fail"


def test_normalize_fresh_from_bench_detail():
    fresh = br.normalize_fresh(
        {"platform": "cpu", "rm": 7, "states_per_sec": 700_000.0,
         "count_ok": True, "lint_ok": True, "full_coverage": True,
         "resume": {"phase": None}}
    )
    assert fresh["platform"] == "cpu"
    assert fresh["value"] == 700_000.0
    assert fresh["resumed"] is None
    assert br.normalize_fresh({"unrelated": 1}) is None


def test_cli_exit_codes_and_artifact(tmp_path):
    arch = _archive(tmp_path, CPU_LINES)
    fresh = tmp_path / "line.json"
    out = tmp_path / "regress.json"

    def run(value, **kw):
        doc = {"metric": "x, spawn_xla, cpu", "value": value, "count_ok": True}
        doc.update(kw)
        fresh.write_text(json.dumps(doc))
        return subprocess.run(
            [sys.executable, TOOL, "--archive", arch, "--fresh", str(fresh),
             "--chaos", str(tmp_path / "absent.json"), "--out", str(out)],
            capture_output=True, text=True,
        )

    proc = run(760_000.0)
    assert proc.returncode == 0, proc.stderr
    banked = json.loads(out.read_text())
    assert banked["verdict"] == "pass"
    assert json.loads(proc.stdout)["verdict"] == "pass"

    assert run(1_000.0).returncode == 1
    assert json.loads(out.read_text())["verdict"] == "fail"

    # Unreadable fresh line: typed error, exit 2.
    proc = subprocess.run(
        [sys.executable, TOOL, "--archive", arch,
         "--fresh", str(tmp_path / "missing.json"), "--out", str(out)],
        capture_output=True, text=True,
    )
    assert proc.returncode == 2
    assert json.loads(proc.stdout)["verdict"] == "error"


@pytest.mark.skipif(
    not os.path.isdir(os.path.join(REPO, "runs", "archive")),
    reason="no committed archive in this tree",
)
def test_self_test_against_committed_archive():
    """The smoke-stage form: the gate proves all three verdicts against
    the REAL runs/archive trajectory."""
    proc = subprocess.run(
        [sys.executable, TOOL, "--self-test"], capture_output=True, text=True,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    line = json.loads(proc.stdout)
    assert line["ok"] is True
    assert line["cases"] == {
        "real_line": "pass", "degraded_line": "fail",
        "empty_archive": "no_baseline",
    }
