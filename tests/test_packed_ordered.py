"""Packed ordered-network single-copy register: FifoLanes end-to-end.

The reference has no exact-count oracle for ordered single-copy configs
(its tests use unordered networks; ``bench.sh:27-34`` runs ordered configs
as benchmarks), so parity here is engine-vs-engine: the packed FifoLanes
model must agree action-for-action and in full coverage with this package's
object ``OrderedNetwork`` model — which itself passes the reference's
ordered-semantics regression matrix (model.rs:795-964).
"""

import random

import numpy as np

from stateright_tpu.actor import Network
from stateright_tpu.models.single_copy_register import (
    PackedSingleCopyRegisterOrdered,
    single_copy_register_model,
)


def test_codec_round_trips_and_differential_step_parity():
    import jax
    import jax.numpy as jnp

    m = PackedSingleCopyRegisterOrdered(2)
    rng = random.Random(13)
    init = m._inner.init_states()[0]
    sample = {init}
    cur = init
    for _ in range(3000):
        steps = list(m._inner.next_steps(cur))
        if not steps:
            cur = init
            continue
        _, cur = rng.choice(steps)
        sample.add(cur)
        if len(sample) >= 120:
            break
    states = sorted(sample, key=repr)

    packed = np.stack([m.pack(s) for s in states])
    for s, row in zip(states, packed):
        assert m.unpack(row) == s, f"codec round-trip mismatch for {s!r}"

    nxt, valid, ovf = jax.jit(jax.vmap(m.packed_step))(jnp.asarray(packed))
    nxt, valid, ovf = np.asarray(nxt), np.asarray(valid), np.asarray(ovf)
    assert not ovf.any(), "codec overflow on reachable states"

    lane_of = {m._lane_key(lane): lane for lane in range(2 * m.C)}
    for si, s in enumerate(states):
        obj = {}
        for action, ns in m._inner.next_steps(s):
            lane = lane_of[(action.src, action.dst)]
            # Ordered semantics: the deliverable envelope IS the lane head.
            assert s.network.flows[(action.src, action.dst)][0] == action.msg
            obj[lane] = ns
        assert set(np.nonzero(valid[si])[0].tolist()) == set(obj), (
            f"enabled-lane mismatch at state {si}: {s!r}"
        )
        for lane, ns in obj.items():
            np.testing.assert_array_equal(
                nxt[si, lane],
                m.pack(ns),
                err_msg=f"successor mismatch: state {si}, lane {lane}",
            )


def test_xla_matches_the_object_engine_end_to_end():
    m = PackedSingleCopyRegisterOrdered(2)
    xc = m.checker().spawn_xla(
        frontier_capacity=1 << 10,
        table_capacity=1 << 12,
        host_verified_cap=1024,
    ).join()
    oracle = (
        single_copy_register_model(2, 1, Network.new_ordered())
        .checker()
        .spawn_bfs()
        .join()
    )
    assert xc.unique_state_count() == oracle.unique_state_count()
    xc.assert_properties()
    oracle.assert_properties()
    # Same reachability witness depth (both are level-order BFS).
    assert len(xc.discoveries()["value chosen"]) == len(
        oracle.discoveries()["value chosen"]
    )
