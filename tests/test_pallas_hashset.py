"""Pallas insert kernel: differential tests against the XLA scatter insert.

Runs in interpret mode on CPU (the kernel auto-detects backend); the
contract is bit-identical results — same is_new/overflow flags and the same
table contents — for any batch, including in-batch duplicates, inactive
lanes, and overflow.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from stateright_tpu.ops import hashset
from stateright_tpu.ops.pallas_hashset import insert_auto, insert_pallas


def _table_set(t):
    kh, kl, vh, vl = (np.asarray(p) for p in t)
    occ = (kh != 0) | (kl != 0)
    return set(zip(kh[occ], kl[occ], vh[occ], vl[occ]))


def _random_batch(n, seed, dup_every=0, inactive_frac=0.0):
    rng = np.random.default_rng(seed)
    fp_hi = rng.integers(1, 2**32, size=n, dtype=np.uint32)
    fp_lo = rng.integers(1, 2**32, size=n, dtype=np.uint32)
    if dup_every:
        for i in range(dup_every, n, dup_every):
            fp_hi[i] = fp_hi[i - dup_every]
            fp_lo[i] = fp_lo[i - dup_every]
    vals = np.arange(1, n + 1, dtype=np.uint32)
    active = rng.random(n) >= inactive_frac
    return (
        jnp.asarray(fp_hi),
        jnp.asarray(fp_lo),
        jnp.asarray(vals),
        jnp.asarray(vals),
        jnp.asarray(active),
    )


@pytest.mark.parametrize("dup_every,inactive_frac", [(0, 0.0), (7, 0.2), (1, 0.5)])
def test_pallas_matches_xla_insert(dup_every, inactive_frac):
    batch = _random_batch(200, seed=3, dup_every=dup_every, inactive_frac=inactive_frac)
    a1, new1, ovf1 = hashset.insert(hashset.make(2048, jnp), *batch)
    a2, new2, ovf2 = insert_pallas(hashset.make(2048, jnp), *batch)
    np.testing.assert_array_equal(np.asarray(new1), np.asarray(new2))
    np.testing.assert_array_equal(np.asarray(ovf1), np.asarray(ovf2))
    assert _table_set(a1) == _table_set(a2)


def test_pallas_duplicate_reinsert_not_new():
    batch = _random_batch(64, seed=4)
    hs, new1, _ = insert_pallas(hashset.make(512, jnp), *batch)
    hs, new2, _ = insert_pallas(hs, *batch)
    assert int(np.asarray(new1).sum()) == 64
    assert int(np.asarray(new2).sum()) == 0


def test_pallas_overflow_reported():
    # 16-slot table, 32 distinct keys, max_probes 4: overflow must fire in
    # both engines. WHICH elements overflow legitimately differs (parallel
    # election vs. sequential fill); both engines discard results and grow
    # on any overflow, so only the any() signal is contractual.
    batch = _random_batch(32, seed=5)
    _, _, ovf_x = hashset.insert(hashset.make(16, jnp), *batch, max_probes=4)
    hs_p, _, ovf_p = insert_pallas(hashset.make(16, jnp), *batch, max_probes=4)
    assert bool(np.asarray(ovf_p).any())
    assert bool(np.asarray(ovf_x).any())
    # Whatever did land in the table is a subset of the batch keys.
    batch_keys = set(zip(np.asarray(batch[0]), np.asarray(batch[1])))
    assert {(k[0], k[1]) for k in _table_set(hs_p)} <= batch_keys


def test_insert_auto_dispatches_small_batch_to_pallas(monkeypatch):
    import stateright_tpu.ops.pallas_hashset as ph

    called = {}

    def spy(*args, **kwargs):
        called["pallas"] = True
        return insert_pallas(*args, **kwargs)

    monkeypatch.setattr(ph, "insert_pallas", spy)
    batch = _random_batch(32, seed=6)
    big = hashset.make(1 << 12, jnp)  # 32 * 64 < 4096: pallas path
    a1, new1, _ = insert_auto(big, *batch)
    assert called.get("pallas"), "small batch must take the Pallas kernel"
    a2, new2, _ = hashset.insert(hashset.make(1 << 12, jnp), *batch)
    np.testing.assert_array_equal(np.asarray(new1), np.asarray(new2))
    assert _table_set(a1) == _table_set(a2)


def test_insert_auto_dispatches_large_batch_to_xla(monkeypatch):
    import stateright_tpu.ops.pallas_hashset as ph

    def boom(*_a, **_k):  # any pallas call would be a dispatch bug
        raise AssertionError("large batch must take the XLA insert")

    monkeypatch.setattr(ph, "insert_pallas", boom)
    batch = _random_batch(128, seed=8)
    small = hashset.make(1 << 10, jnp)  # 128 * 64 >= 1024: XLA path
    hs, new, ovf = insert_auto(small, *batch, max_probes=16)
    assert int(np.asarray(new).sum()) == 128
    assert not bool(np.asarray(ovf).any())
