"""Semantics-layer tests, porting the reference's cases:

- spec objects: semantics/register.rs:51-87, vec.rs:52-99,
  write_once_register.rs:60-113
- linearizability: semantics/linearizability.rs:314-513
- sequential consistency: semantics/sequential_consistency.rs:270-379
"""

import pytest

from stateright_tpu.semantics import (
    HistoryError,
    LinearizabilityTester,
    SequentialConsistencyTester,
)
from stateright_tpu.semantics.register import Read, ReadOk, Register, Write, WriteOk
from stateright_tpu.semantics import vec
from stateright_tpu.semantics import write_once_register as wor
from stateright_tpu.semantics.vec import Len, LenOk, Pop, PopOk, Push, PushOk, VecSpec


class TestRegisterSpec:
    def test_models_expected_semantics(self):
        r = Register("A")
        assert r.invoke(Read()) == ReadOk("A")
        assert r.invoke(Write("B")) == WriteOk()
        assert r.invoke(Read()) == ReadOk("B")

    def test_accepts_valid_histories(self):
        assert Register("A").is_valid_history([])
        assert Register("A").is_valid_history(
            [
                (Read(), ReadOk("A")),
                (Write("B"), WriteOk()),
                (Read(), ReadOk("B")),
                (Write("C"), WriteOk()),
                (Read(), ReadOk("C")),
            ]
        )

    def test_rejects_invalid_histories(self):
        assert not Register("A").is_valid_history(
            [(Read(), ReadOk("B")), (Write("B"), WriteOk())]
        )
        assert not Register("A").is_valid_history(
            [(Write("B"), WriteOk()), (Read(), ReadOk("A"))]
        )


class TestVecSpec:
    def test_models_expected_semantics(self):
        v = VecSpec(("A",))
        assert v.invoke(Len()) == LenOk(1)
        assert v.invoke(Push("B")) == PushOk()
        assert v.invoke(Len()) == LenOk(2)
        assert v.invoke(Pop()) == PopOk("B")
        assert v.invoke(Len()) == LenOk(1)
        assert v.invoke(Pop()) == PopOk("A")
        assert v.invoke(Len()) == LenOk(0)
        assert v.invoke(Pop()) == PopOk(None)

    def test_accepts_valid_histories(self):
        assert VecSpec().is_valid_history([])
        assert VecSpec().is_valid_history(
            [
                (Push(10), PushOk()),
                (Push(20), PushOk()),
                (Len(), LenOk(2)),
                (Pop(), PopOk(20)),
                (Len(), LenOk(1)),
                (Pop(), PopOk(10)),
                (Len(), LenOk(0)),
                (Pop(), PopOk(None)),
            ]
        )

    def test_rejects_invalid_histories(self):
        assert not VecSpec().is_valid_history(
            [(Push(10), PushOk()), (Push(20), PushOk()), (Len(), LenOk(1))]
        )
        assert not VecSpec().is_valid_history(
            [(Push(10), PushOk()), (Push(20), PushOk()), (Pop(), PopOk(10))]
        )


class TestWORegisterSpec:
    def test_models_expected_semantics(self):
        r = wor.WORegister(None)
        assert r.invoke(wor.Write("A")) == wor.WriteOk()
        assert r.invoke(wor.Read()) == wor.ReadOk("A")
        assert r.invoke(wor.Write("B")) == wor.WriteFail()
        assert r.invoke(wor.Read()) == wor.ReadOk("A")

    def test_accepts_valid_histories(self):
        assert wor.WORegister(None).is_valid_history([])
        assert wor.WORegister(None).is_valid_history(
            [
                (wor.Read(), wor.ReadOk(None)),
                (wor.Write("A"), wor.WriteOk()),
                (wor.Read(), wor.ReadOk("A")),
                (wor.Write("B"), wor.WriteFail()),
                (wor.Read(), wor.ReadOk("A")),
                (wor.Write("C"), wor.WriteFail()),
                (wor.Read(), wor.ReadOk("A")),
            ]
        )

    def test_rejects_invalid_histories(self):
        assert not wor.WORegister("A").is_valid_history(
            [(wor.Read(), wor.ReadOk("A")), (wor.Write("B"), wor.WriteOk())]
        )
        assert not wor.WORegister(None).is_valid_history(
            [(wor.Read(), wor.ReadOk("A")), (wor.Write("A"), wor.WriteOk())]
        )
        assert not wor.WORegister(None).is_valid_history(
            [
                (wor.Read(), wor.ReadOk(None)),
                (wor.Write("A"), wor.WriteOk()),
                (wor.Write("B"), wor.WriteOk()),
            ]
        )


class TestLinearizability:
    def test_rejects_invalid_history(self):
        t = LinearizabilityTester(Register("A"))
        t.on_invoke(99, Write("B"))
        with pytest.raises(HistoryError):
            t.on_invoke(99, Write("C"))
        assert not t.is_consistent()

        t = LinearizabilityTester(Register("A"))
        t.on_invret(99, Write("B"), WriteOk()).on_invret(99, Write("C"), WriteOk())
        with pytest.raises(HistoryError):
            t.on_return(99, WriteOk())
        assert not t.is_consistent()

    def test_identifies_linearizable_register_history(self):
        t = LinearizabilityTester(Register("A"))
        t.on_invoke(0, Write("B"))
        t.on_invret(1, Read(), ReadOk("A"))
        assert t.serialized_history() == [(Read(), ReadOk("A"))]

        t = LinearizabilityTester(Register("A"))
        t.on_invoke(0, Read())
        t.on_invoke(1, Write("B"))
        t.on_return(0, ReadOk("B"))
        assert t.serialized_history() == [
            (Write("B"), WriteOk()),
            (Read(), ReadOk("B")),
        ]

    def test_identifies_unlinearizable_register_history(self):
        t = LinearizabilityTester(Register("A"))
        t.on_invret(0, Read(), ReadOk("B"))
        assert t.serialized_history() is None

        # SC but not linearizable: the read completed before the write began.
        t = LinearizabilityTester(Register("A"))
        t.on_invret(0, Read(), ReadOk("B"))
        t.on_invoke(1, Write("B"))
        assert t.serialized_history() is None

    def test_identifies_linearizable_vec_history(self):
        t = LinearizabilityTester(VecSpec())
        t.on_invoke(0, Push(10))
        assert t.serialized_history() == []

        t = LinearizabilityTester(VecSpec())
        t.on_invoke(0, Push(10))
        t.on_invret(1, Pop(), PopOk(None))
        assert t.serialized_history() == [(Pop(), PopOk(None))]

        t = LinearizabilityTester(VecSpec())
        t.on_invoke(0, Push(10))
        t.on_invret(1, Pop(), PopOk(10))
        assert t.serialized_history() == [
            (Push(10), PushOk()),
            (Pop(), PopOk(10)),
        ]

        t = LinearizabilityTester(VecSpec())
        t.on_invret(0, Push(10), PushOk())
        t.on_invoke(0, Push(20))
        t.on_invret(1, Len(), LenOk(1))
        t.on_invret(1, Pop(), PopOk(20))
        t.on_invret(1, Pop(), PopOk(10))
        assert t.serialized_history() == [
            (Push(10), PushOk()),
            (Len(), LenOk(1)),
            (Push(20), PushOk()),
            (Pop(), PopOk(20)),
            (Pop(), PopOk(10)),
        ]

        t = LinearizabilityTester(VecSpec())
        t.on_invret(0, Push(10), PushOk())
        t.on_invoke(0, Push(20))
        t.on_invret(1, Len(), LenOk(1))
        t.on_invret(1, Pop(), PopOk(10))
        t.on_invret(1, Pop(), PopOk(20))
        assert t.serialized_history() == [
            (Push(10), PushOk()),
            (Len(), LenOk(1)),
            (Pop(), PopOk(10)),
            (Push(20), PushOk()),
            (Pop(), PopOk(20)),
        ]

        t = LinearizabilityTester(VecSpec())
        t.on_invret(0, Push(10), PushOk())
        t.on_invoke(0, Push(20))
        t.on_invret(1, Len(), LenOk(2))
        t.on_invret(1, Pop(), PopOk(20))
        t.on_invret(1, Pop(), PopOk(10))
        assert t.serialized_history() == [
            (Push(10), PushOk()),
            (Push(20), PushOk()),
            (Len(), LenOk(2)),
            (Pop(), PopOk(20)),
            (Pop(), PopOk(10)),
        ]

        t = LinearizabilityTester(VecSpec())
        t.on_invret(0, Push(10), PushOk())
        t.on_invoke(1, Len())
        t.on_invoke(0, Push(20))
        t.on_return(1, LenOk(1))
        assert t.serialized_history() == [
            (Push(10), PushOk()),
            (Len(), LenOk(1)),
        ]

        t = LinearizabilityTester(VecSpec())
        t.on_invret(0, Push(10), PushOk())
        t.on_invoke(1, Len())
        t.on_invoke(0, Push(20))
        t.on_return(1, LenOk(2))
        assert t.serialized_history() == [
            (Push(10), PushOk()),
            (Push(20), PushOk()),
            (Len(), LenOk(2)),
        ]

    def test_identifies_unlinearizable_vec_history(self):
        # SC but not linearizable.
        t = LinearizabilityTester(VecSpec())
        t.on_invret(0, Push(10), PushOk())
        t.on_invret(1, Pop(), PopOk(None))
        assert t.serialized_history() is None

        t = LinearizabilityTester(VecSpec())
        t.on_invret(0, Push(10), PushOk())
        t.on_invoke(1, Len())
        t.on_invoke(0, Push(20))
        t.on_return(1, LenOk(0))
        assert t.serialized_history() is None

        t = LinearizabilityTester(VecSpec())
        t.on_invret(0, Push(10), PushOk())
        t.on_invoke(0, Push(20))
        t.on_invret(1, Len(), LenOk(2))
        t.on_invret(1, Pop(), PopOk(10))
        t.on_invret(1, Pop(), PopOk(20))
        assert t.serialized_history() is None


class TestSequentialConsistency:
    def test_rejects_invalid_history(self):
        t = SequentialConsistencyTester(Register("A"))
        t.on_invoke(99, Write("B"))
        with pytest.raises(HistoryError):
            t.on_invoke(99, Write("C"))
        assert not t.is_consistent()

    def test_identifies_serializable_register_history(self):
        t = SequentialConsistencyTester(Register("A"))
        t.on_invoke(0, Write("B"))
        t.on_invret(1, Read(), ReadOk("A"))
        assert t.serialized_history() == [(Read(), ReadOk("A"))]

        # Not linearizable, but SC: thread 1's write serializes first.
        t = SequentialConsistencyTester(Register("A"))
        t.on_invret(0, Read(), ReadOk("B"))
        t.on_invoke(1, Write("B"))
        assert t.serialized_history() == [
            (Write("B"), WriteOk()),
            (Read(), ReadOk("B")),
        ]

    def test_identifies_unserializable_register_history(self):
        t = SequentialConsistencyTester(Register("A"))
        t.on_invret(0, Read(), ReadOk("B"))
        assert t.serialized_history() is None

    def test_identifies_serializable_vec_history(self):
        t = SequentialConsistencyTester(VecSpec())
        t.on_invoke(0, Push(10))
        assert t.serialized_history() == []

        t = SequentialConsistencyTester(VecSpec())
        t.on_invoke(0, Push(10))
        t.on_invret(1, Pop(), PopOk(None))
        assert t.serialized_history() == [(Pop(), PopOk(None))]

        t = SequentialConsistencyTester(VecSpec())
        t.on_invret(1, Pop(), PopOk(10))
        t.on_invret(0, Push(10), PushOk())
        t.on_invret(0, Pop(), PopOk(20))
        t.on_invoke(0, Push(30))
        t.on_invret(1, Push(20), PushOk())
        t.on_invret(1, Pop(), PopOk(None))
        assert t.serialized_history() == [
            (Push(10), PushOk()),
            (Pop(), PopOk(10)),
            (Push(20), PushOk()),
            (Pop(), PopOk(20)),
            (Pop(), PopOk(None)),
        ]

    def test_identifies_unserializable_vec_history(self):
        t = SequentialConsistencyTester(VecSpec())
        t.on_invret(0, Push(10), PushOk())
        t.on_invoke(0, Push(20))
        t.on_invret(1, Len(), LenOk(2))
        t.on_invret(1, Pop(), PopOk(10))
        t.on_invret(1, Pop(), PopOk(20))
        assert t.serialized_history() is None


class TestTesterValueSemantics:
    """Testers ride in fingerprinted ActorModel history state, so they need
    clone/eq/hash value semantics (the reference derives Clone/Hash/Eq)."""

    def test_clone_is_independent(self):
        t = LinearizabilityTester(Register("A"))
        t.on_invoke(0, Write("B"))
        dup = t.clone()
        dup.on_return(0, WriteOk())
        assert len(t) == 1 and len(dup) == 1
        assert t != dup
        assert t.in_flight_by_thread and not dup.in_flight_by_thread

    def test_eq_and_hash(self):
        def build():
            t = SequentialConsistencyTester(VecSpec())
            t.on_invret(0, Push(1), PushOk())
            t.on_invoke(1, Pop())
            return t

        a, b = build(), build()
        assert a == b and hash(a) == hash(b)

        from stateright_tpu.fingerprint import fingerprint

        assert fingerprint(a) == fingerprint(b)
        b.on_return(1, PopOk(1))
        assert fingerprint(a) != fingerprint(b)
