"""Packed actor models on the device engine: the ActorModel fixture must
match the object-level oracle exactly (states, totals, discoveries) across
network configurations, on both the single-chip and sharded engines."""

import numpy as np
import pytest

import jax

from stateright_tpu.actor.actor_test_util import PingPongCfg, ping_pong_model
from stateright_tpu.actor.packed import PackedPingPong
from stateright_tpu.fingerprint import fingerprint
from stateright_tpu.parallel import default_mesh


def _object_checker(cfg, lossy):
    model = ping_pong_model(cfg)
    if lossy:
        model = model.lossy_network(True)
    return model.checker().spawn_bfs().join()


def _packed_checker(cfg, lossy, mesh=None):
    return (
        PackedPingPong(cfg, lossy=lossy)
        .checker()
        .spawn_xla(mesh=mesh, frontier_capacity=1 << 12, table_capacity=1 << 15)
        .join()
    )


@pytest.mark.parametrize(
    "cfg,lossy",
    [
        (PingPongCfg(False, 5), True),  # reference oracle: 4,094 states
        (PingPongCfg(False, 3), False),
        (PingPongCfg(True, 3), True),  # with history counters in the state
    ],
)
def test_packed_ping_pong_matches_object_oracle(cfg, lossy):
    obj = _object_checker(cfg, lossy)
    dev = _packed_checker(cfg, lossy)
    assert dev.unique_state_count() == obj.unique_state_count()
    assert dev.state_count() == obj.state_count()
    assert dev.max_depth() == obj.max_depth()
    assert set(dev.discoveries()) == set(obj.discoveries())


def test_packed_ping_pong_lossy_max5_is_4094():
    dev = _packed_checker(PingPongCfg(False, 5), lossy=True)
    assert dev.unique_state_count() == 4_094  # model.rs:680


def test_packed_codec_roundtrip_and_fingerprint_agreement():
    model = PackedPingPong(PingPongCfg(True, 4), lossy=True)
    seen = 0
    frontier = model.init_states()
    for _ in range(3):
        nxt = []
        for s in frontier:
            rt = model.unpack(model.pack(s))
            assert rt == s, f"codec round-trip broke: {rt!r} != {s!r}"
            assert fingerprint(rt) == fingerprint(s)
            seen += 1
            nxt.extend(s2 for _a, s2 in model.next_steps(s))
        frontier = nxt[:16]
    assert seen > 1


def test_packed_discovery_paths_replay_on_object_model():
    dev = _packed_checker(PingPongCfg(False, 3), lossy=True)
    assert dev.discoveries()
    model = dev.model()
    for name, path in dev.discoveries().items():
        # Witness paths are object-level ActorModelState sequences; replay
        # each step through the object model and check the successor chain.
        pairs = path.into_vec()
        assert hasattr(pairs[-1][0], "actor_states")
        for (state, action), (next_state, _a) in zip(pairs, pairs[1:]):
            assert action is not None
            assert model.next_state(state, action) == next_state


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs the 8-device mesh")
def test_packed_ping_pong_on_sharded_mesh():
    obj = _object_checker(PingPongCfg(False, 5), lossy=True)
    dev = _packed_checker(PingPongCfg(False, 5), lossy=True, mesh=default_mesh(8))
    assert dev.unique_state_count() == obj.unique_state_count() == 4_094
    assert dev.state_count() == obj.state_count()
    assert set(dev.discoveries()) == set(obj.discoveries())
