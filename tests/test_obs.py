"""The observability layer (stateright_tpu/obs; docs/observability.md):
span JSONL schema, Chrome trace-event export validity, the heartbeat
protocol, the unified ``checker.metrics()`` snapshot, the normalized
``dispatch_log`` shape, the metrics time-series recorder (row schema,
keep-K rotation, quiescent-boundary-only sampling), and the
zero-overhead guarantee with tracing/recording off.

These are SCHEMA pins: consumers (tools/roofline.py --measured, the
bench watchdog, tools/tpu_watch.sh, Perfetto, obs/promexport.py, the
``/.dash`` dashboard) parse these artifacts, so a key rename here is a
breaking change, not a refactor.
"""

import json
import os

import pytest

from stateright_tpu import obs
from stateright_tpu.models.two_phase_commit import PackedTwoPhaseSys
from stateright_tpu.obs import heartbeat as hb_mod
from stateright_tpu.parallel import default_mesh

KW = dict(frontier_capacity=1 << 10, table_capacity=1 << 13)

#: ONE shared model instance: compiled supersteps cache on the model, so
#: every test after the first reuses the XLA programs instead of paying a
#: fresh compile per spawn (~3 s each on this 1-core box). Every spawn in
#: this file passes explicit capacities, so learned capacity hints from
#: growth-exercising tests never change another test's schedule.
MODEL = PackedTwoPhaseSys(3)


def _spawn(**kw):
    merged = {**KW, **kw}
    return MODEL.checker().spawn_xla(**merged)

#: The span-line schema (exactly these keys, docs/observability.md).
#: ``span_id`` joined the pin in the distributed-tracing round; records
#: from a tracer carrying a trace CONTEXT additionally hold
#: ``trace_id``/``parent_id`` (CTX_SPAN_KEYS) — absent otherwise, so
#: context-less traces stay byte-compatible with older consumers.
SPAN_KEYS = {"ts", "dur", "name", "span_id", "attrs"}
CTX_SPAN_KEYS = SPAN_KEYS | {"trace_id", "parent_id"}
#: Attributes every dispatch span carries.
DISPATCH_ATTRS = {
    "flavor", "bucket", "cand", "committed", "compile", "retry",
    "dedup", "compaction",
}
#: The stable device-engine metrics key set (single-chip engine; the mesh
#: engine adds mesh gauges on top of the same set).
METRIC_KEYS = {
    "engine", "backend", "dedup", "compaction", "symmetry", "ladder",
    "cand_ladder_k",
    "shrink_exit", "levels_per_dispatch", "state_count",
    "unique_state_count", "depth", "max_depth", "frontier_count",
    "frontier_capacity", "table_capacity", "table_occupancy", "dispatches",
    "levels_committed", "cand_retries", "hv", "table_grows",
    "frontier_grows", "cand_grows", "delta_flushes", "shrink_exits",
    "ladder_jumps",
    # recovery keys (docs/observability.md "Recovery"): the auto-
    # checkpoint config gauge, resume provenance, the last checkpointed
    # level, and the write counter.
    "checkpoint_to", "resumed_from", "last_checkpoint_level",
    "checkpoints_written",
    # time-series config gauge (docs/observability.md "Time series").
    "metrics_to",
}

#: The metrics time-series row schema (exactly these keys;
#: docs/observability.md "Time series" — promexport, the dashboard, and
#: roofline's series mode parse these).
RECORDER_ROW_KEYS = {"v", "unix_ts", "t", "seq", "kind", "metrics"}


def _spans(path):
    with open(path) as fh:
        return [json.loads(line) for line in fh]


# --- span JSONL -----------------------------------------------------------


def test_span_jsonl_schema(tmp_path):
    trace = str(tmp_path / "trace.jsonl")
    c = _spawn(trace=trace).join()
    assert c.unique_state_count() == 288
    lines = _spans(trace)
    assert lines, "trace is empty"
    for rec in lines:
        assert set(rec) == SPAN_KEYS, rec  # no ctx set -> no ctx keys
        assert isinstance(rec["ts"], (int, float)) and rec["ts"] >= 0
        assert isinstance(rec["dur"], (int, float)) and rec["dur"] >= 0
        assert isinstance(rec["name"], str)
        assert isinstance(rec["span_id"], str)
        assert isinstance(rec["attrs"], dict)
    assert len({r["span_id"] for r in lines}) == len(lines)
    assert lines[0]["name"] == "trace_start"
    assert {"pid", "unix_ts"} <= set(lines[0]["attrs"])
    disp = [r for r in lines if r["name"] == "dispatch"]
    assert disp, "no dispatch spans"
    for rec in disp:
        assert DISPATCH_ATTRS <= set(rec["attrs"]), rec["attrs"]
    # Span-level accounting agrees with the engine's own telemetry: one
    # span per device call, committed levels summing to the level log.
    assert len(disp) == len(c.dispatch_log)
    assert sum(r["attrs"]["committed"] for r in disp) == len(c.level_log)
    # The first call of each bucket compiles; 2pc(3) from a cold model
    # compiles at least its first program.
    assert any(r["attrs"]["compile"] for r in disp)


def test_trace_env_knob(tmp_path, monkeypatch):
    trace = str(tmp_path / "env_trace.jsonl")
    monkeypatch.setenv("STPU_TRACE", trace)
    c = _spawn().join()
    assert c._tracer.enabled
    assert any(r["name"] == "dispatch" for r in _spans(trace))


# --- Chrome export --------------------------------------------------------


def test_chrome_export_valid(tmp_path):
    trace = str(tmp_path / "trace.jsonl")
    out = str(tmp_path / "chrome.json")
    _spawn(trace=trace).join()
    n = obs.export_chrome(trace, out)
    assert n > 0
    with open(out) as fh:
        doc = json.load(fh)
    events = doc["traceEvents"]
    assert len(events) == n
    for ev in events:
        # The Chrome trace-event contract Perfetto loads: complete ("X")
        # events with microsecond ts/dur and pid/tid lanes, plus "C"
        # counter samples for spans carrying mux-lane telemetry.
        assert ev["ph"] in ("X", "C")
        if ev["ph"] == "X":
            assert {"name", "cat", "ph", "ts", "dur", "pid", "tid",
                    "args"} <= set(ev)
            assert isinstance(ev["dur"], (int, float))
        assert isinstance(ev["ts"], (int, float))
        assert isinstance(ev["args"], dict)


def test_chrome_env_knob_exports_on_close(tmp_path, monkeypatch):
    trace = str(tmp_path / "trace.jsonl")
    chrome = str(tmp_path / "chrome.json")
    monkeypatch.setenv("STPU_TRACE", trace)
    monkeypatch.setenv("STPU_TRACE_CHROME", chrome)
    c = _spawn().join()
    c._tracer.close()  # atexit does this in real runs
    with open(chrome) as fh:
        assert json.load(fh)["traceEvents"]


def test_chrome_mux_lane_counter_track():
    """A span carrying ``lanes_active`` renders as a Perfetto counter
    track ("C" event, lanes_active + derived lanes_idle) next to its
    slice — the mux lane-occupancy chart."""
    from stateright_tpu.obs.trace import chrome_events

    rec = {"ts": 1.5, "dur": 0.25, "name": "dispatch", "span_id": "a.1",
           "attrs": {"flavor": "mux", "lanes": 4, "lanes_active": 3}}
    evs = chrome_events(rec, pid=7, tid=2)
    assert [e["ph"] for e in evs] == ["X", "C"]
    slice_, counter = evs
    assert slice_["ts"] == counter["ts"] == 1.5e6
    assert counter["name"] == "mux lanes"
    assert counter["args"] == {"lanes_active": 3, "lanes_idle": 1}
    # Context ids ride in the slice's args when present.
    rec2 = dict(rec, trace_id="t" * 16, parent_id="a.0")
    args = chrome_events(rec2, pid=7, tid=2)[0]["args"]
    assert args["trace_id"] == "t" * 16 and args["parent_id"] == "a.0"


# --- distributed tracing (docs/observability.md "Distributed tracing") ----


def test_trace_ctx_env_inheritance(tmp_path, monkeypatch):
    """STPU_TRACE_CTX is the cross-process seam: a tracer constructed
    under it stamps every record with the trace id and defaults parents
    to the context's span — engine spans in a worker join the
    submission's trace with zero engine changes."""
    from stateright_tpu.obs import trace as trace_mod

    tid = trace_mod.new_trace_id()
    assert len(tid) == 16
    monkeypatch.setenv(trace_mod.CTX_ENV, trace_mod.format_ctx(tid, "p.9"))
    trace = str(tmp_path / "trace.jsonl")
    c = _spawn(trace=trace).join()
    assert c._tracer.trace_id == tid
    lines = _spans(trace)
    for rec in lines:
        assert SPAN_KEYS <= set(rec) <= CTX_SPAN_KEYS, rec
        assert rec["trace_id"] == tid
        assert rec["parent_id"] == "p.9"
    # Malformed ctx degrades to context-less tracing, not a failure.
    assert trace_mod.parse_ctx(":") is None
    assert trace_mod.parse_ctx("") is None
    assert trace_mod.parse_ctx("abc") == ("abc", None)


def test_tracer_emit_overrides_and_preallocated_ids(tmp_path):
    """Tracer.emit's per-record overrides: a shared tracer (one service
    file, many jobs) stamps per-job trace ids without mutating ambient
    state, and new_span_id pre-allocates so children can reference a
    span emitted after they finish (the attempt span)."""
    from stateright_tpu.obs.trace import Tracer

    path = str(tmp_path / "t.jsonl")
    tr = Tracer(path)
    pre = tr.new_span_id()
    child = tr.emit("child", t0=0.0, dur=0.1, parent_id=pre,
                    trace_id="aaaa", attrs={"k": 1})
    got = tr.emit("parent", t0=0.0, dur=0.2, trace_id="bbbb", span_id=pre)
    assert got == pre and child != pre
    tr.emit("ambient", t0=0.0, dur=0.0)
    tr.close()
    recs = {r["name"]: r for r in _spans(path)}
    assert recs["child"]["trace_id"] == "aaaa"
    assert recs["child"]["parent_id"] == pre
    assert recs["parent"]["trace_id"] == "bbbb"
    assert recs["parent"]["span_id"] == pre
    assert "trace_id" not in recs["ambient"]  # no ambient ctx set


# --- dispatch-phase profiler ----------------------------------------------


def test_phases_profiler_rows_and_spans(tmp_path):
    """``spawn_xla(phases=True)``: every device call logs a phase_log
    row whose host_prep/enqueue/device_compute/readback partition the
    parent dispatch span, and emits ``phase:*`` sub-spans parented to
    the dispatch span's id (tools/roofline.py --phases consumes both)."""
    trace = str(tmp_path / "trace.jsonl")
    c = _spawn(trace=trace, phases=True).join()
    assert c.unique_state_count() == 288
    rows = c.phase_log
    assert len(rows) == len(c.dispatch_log)
    for row in rows:
        assert {"bucket", "flavor", "compile", "committed"} <= set(row)
        assert all(row[k] >= 0 for k in c.PHASE_NAMES)
    lines = _spans(trace)
    disp = {r["span_id"]: r for r in lines if r["name"] == "dispatch"}
    phase = [r for r in lines if r["name"].startswith("phase:")]
    assert len(phase) == len(rows) * len(c.PHASE_NAMES)
    for rec in phase:
        assert rec["parent_id"] in disp, rec
    # Phases partition their dispatch: the four sub-spans sum to the
    # parent's wall-clock minus only the inter-stamp bookkeeping.
    by_parent = {}
    for rec in phase:
        by_parent.setdefault(rec["parent_id"], 0.0)
        by_parent[rec["parent_id"]] += rec["dur"]
    for sid, total in by_parent.items():
        assert 0.0 <= disp[sid]["dur"] - total < 0.05, (sid, total)


def test_phases_env_knob(tmp_path, monkeypatch):
    monkeypatch.setenv("STPU_TRACE", str(tmp_path / "t.jsonl"))
    monkeypatch.setenv("STPU_PHASES", "1")
    c = _spawn().join()
    assert c._phases and len(c.phase_log) == len(c.dispatch_log)
    with pytest.raises(ValueError, match="STPU_PHASES"):
        monkeypatch.setenv("STPU_PHASES", "maybe")
        _spawn()


def test_phases_require_tracer():
    """phases=True without a trace sink is inert (nowhere to emit the
    sub-spans), not an error — the flag gates on tracer.enabled."""
    c = _spawn(phases=True).join()
    assert not c._phases and c.phase_log == []


# --- heartbeat ------------------------------------------------------------


def test_heartbeat_advances_once_per_committed_dispatch(tmp_path):
    hb = str(tmp_path / "hb.json")
    c = _spawn(heartbeat=hb, levels_per_dispatch=1)
    mtime0 = None
    while not c.is_done():
        c._run_block()
        rec = hb_mod.read(hb)
        # One seq bump per completed device dispatch — the same unit as
        # one dispatch_log entry — and the commit beat marks idle.
        assert rec is not None
        assert rec["seq"] == len(c.dispatch_log)
        assert rec["phase"] == "idle"
        mtime = os.stat(hb).st_mtime_ns
        if mtime0 is not None:
            assert mtime >= mtime0
        mtime0 = mtime
    assert c.unique_state_count() == 288
    rec = hb_mod.read(hb)
    assert rec["seq"] == len(c.dispatch_log) > 0
    assert {"ts", "seq", "phase", "depth", "states"} <= set(rec)
    assert hb_mod.age_s(hb) is not None


def test_heartbeat_mtime_advances_between_dispatches(tmp_path):
    hb = str(tmp_path / "hb.json")
    c = _spawn(heartbeat=hb, levels_per_dispatch=1)
    stamps = []
    while not c.is_done():
        c._run_block()
        stamps.append((os.stat(hb).st_mtime_ns, hb_mod.read(hb)["seq"]))
    seqs = [s for _, s in stamps]
    assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)
    mts = [m for m, _ in stamps]
    assert mts == sorted(mts)
    assert mts[-1] > mts[0]


# --- metrics --------------------------------------------------------------


@pytest.mark.parametrize("dedup", ["hash", "sorted", "delta"])
def test_metrics_keys_across_dedups(dedup):
    c = _spawn(dedup=dedup).join()
    m = c.metrics()
    assert METRIC_KEYS <= set(m), METRIC_KEYS - set(m)
    assert m["engine"] == "xla"
    assert m["dedup"] == dedup
    assert m["state_count"] == c.state_count() == 1146
    assert m["unique_state_count"] == 288
    assert m["dispatches"] == len(c.dispatch_log)
    assert m["levels_committed"] == len(c.level_log)
    assert 0 < m["table_occupancy"] <= 1
    for counter in (
        "table_grows", "frontier_grows", "cand_grows", "delta_flushes",
        "shrink_exits", "ladder_jumps", "checkpoints_written",
    ):
        assert isinstance(m[counter], int) and m[counter] >= 0
    # No checkpointing configured on this spawn: the recovery gauges read
    # as the documented "off" values.
    assert m["checkpoint_to"] is None
    assert m["resumed_from"] is None
    assert m["last_checkpoint_level"] is None
    assert m["checkpoints_written"] == 0
    json.dumps(m)  # the snapshot is JSON-serializable as-is


def test_metrics_counts_growth_events():
    # A deliberately undersized table forces visited-set growth; the
    # event lands in the unified snapshot.
    c = _spawn(table_capacity=1 << 6).join()
    assert c.unique_state_count() == 288
    assert c.metrics()["table_grows"] >= 1


def test_base_checker_metrics():
    from stateright_tpu.models.two_phase_commit import TwoPhaseSys

    c = TwoPhaseSys(2).checker().spawn_bfs().join()
    m = c.metrics()
    assert {"engine", "state_count", "unique_state_count", "max_depth"} <= set(m)
    assert m["state_count"] == c.state_count()


def test_explorer_status_carries_metrics():
    from stateright_tpu.checker.explorer import make_app

    app, _ = make_app(
        PackedTwoPhaseSys(2).checker(),
        frontier_capacity=1 << 8, table_capacity=1 << 10,
    )
    status = app.status()
    m = status["metrics"]
    assert m["engine"] == "xla"
    assert "pending_pool" in m and "waiting" in m  # on-demand gauges
    # Recovery state is part of the status surface: a wedged interactive
    # session must be diagnosable (and resumable) from /.status alone.
    assert "last_checkpoint" in status
    # Liveness too: heartbeat_age_s rides next to last_checkpoint — None
    # here (no heartbeat configured), a float age when the protocol is on.
    assert status["heartbeat_age_s"] is None


def test_checkpoint_span_per_write(tmp_path):
    # Every auto-checkpoint write emits one "checkpoint" span whose attrs
    # name the file, the depth it captured, and the rotation bound — and
    # the span count agrees with the checkpoints_written counter.
    trace = str(tmp_path / "ck_trace.jsonl")
    ck = str(tmp_path / "ck.npz")
    c = _spawn(
        trace=trace, checkpoint_to=ck, checkpoint_every=1,
        levels_per_dispatch=1,
    ).join()
    m = c.metrics()
    assert m["checkpoints_written"] >= 1
    assert m["checkpoint_to"] == ck
    assert m["last_checkpoint_level"] is not None
    spans = [r for r in _spans(trace) if r["name"] == "checkpoint"]
    assert len(spans) == m["checkpoints_written"]
    for rec in spans:
        assert {"path", "depth", "keep"} <= set(rec["attrs"])


# --- metrics time-series recorder ----------------------------------------


def test_recorder_rows_schema_and_quiescent_cadence(tmp_path):
    from stateright_tpu.obs import read_series

    series = str(tmp_path / "metrics.jsonl")
    # Level cadence 1 + one level per dispatch: a sample opportunity at
    # every quiescent boundary, so the series traces the whole run.
    c = _spawn(metrics_to=series, metrics_every=1, levels_per_dispatch=1).join()
    assert c.unique_state_count() == 288
    assert c.metrics()["metrics_to"] == series
    rows = read_series(series)
    assert rows, "series is empty"
    for rec in rows:
        assert set(rec) == RECORDER_ROW_KEYS, rec
        assert rec["v"] == 1
        assert rec["kind"] == "engine"
        assert isinstance(rec["t"], (int, float)) and rec["t"] >= 0
        # Each row embeds a full metrics() snapshot (stable key set).
        assert METRIC_KEYS <= set(rec["metrics"]), rec["metrics"]
    assert [r["seq"] for r in rows] == list(range(len(rows)))
    # Quiescent-boundary-only sampling: never more samples than device
    # dispatches (each dispatch ends in at most one quiescent point) and
    # the embedded progress gauges advance monotonically.
    assert len(rows) <= len(c.dispatch_log)
    depths = [r["metrics"]["depth"] for r in rows]
    states = [r["metrics"]["state_count"] for r in rows]
    assert depths == sorted(depths)
    assert states == sorted(states)
    # The wall-clock cadence spec parses too (no run needed to pin the
    # grammar — it is the checkpoint module's).
    from stateright_tpu.obs import MetricsRecorder

    r = MetricsRecorder(str(tmp_path / "w.jsonl"), every="2.5s")
    assert r.every_seconds == 2.5 and r.every_levels is None
    with pytest.raises(ValueError):
        MetricsRecorder(str(tmp_path / "bad.jsonl"), every="nope")


def test_recorder_rotation_and_torn_tail(tmp_path):
    from stateright_tpu.obs import MetricsRecorder, read_series
    from stateright_tpu.obs.timeseries import series_files

    base = str(tmp_path / "metrics.jsonl")
    rec = MetricsRecorder(base, every=1, keep=3, rotate_rows=4)
    for i in range(10):
        rec.sample({"state_count": i})
    # 10 rows at 4/file: two full rotations + 2 live rows, keep=3 retains
    # all of them; the chain reads back oldest-first and in order.
    assert series_files(base) == [f"{base}.2", f"{base}.1", base]
    rows = read_series(base)
    assert [r["metrics"]["state_count"] for r in rows] == list(range(10))
    assert [r["seq"] for r in rows] == list(range(10))
    # keep bounds the chain: 8 more rows shift two more rotations and the
    # oldest files fall off the end.
    for i in range(10, 18):
        rec.sample({"state_count": i})
    assert series_files(base) == [f"{base}.2", f"{base}.1", base]
    rows = read_series(base)
    # rows 0..7 fell off the end of the keep=3 chain; 8..17 survive.
    assert [r["metrics"]["state_count"] for r in rows] == list(range(8, 18))
    # A torn tail (kill mid-append) is skipped, not fatal; the window
    # argument trims to the newest N.
    rec.sample({"state_count": 99})
    rec.close()
    with open(base, "a") as fh:
        fh.write('{"v": 1, "metrics": {"state_coun')
    rows = read_series(base)
    assert rows[-1]["metrics"]["state_count"] == 99
    assert [r["metrics"]["state_count"] for r in read_series(base, window=2)] == [17, 99]
    # A recorder RE-OPENED over the torn file (the requeued worker's
    # resume path) repairs the tail first: its next row lands on its own
    # line instead of concatenating onto the fragment and vanishing.
    rec2 = MetricsRecorder(base, every=1, keep=3, rotate_rows=100)
    rec2.sample({"state_count": 100})
    rows = read_series(base)
    assert [r["metrics"]["state_count"] for r in rows[-2:]] == [99, 100]
    rec2.close()


def test_recorder_env_knob(tmp_path, monkeypatch):
    from stateright_tpu.obs import read_series

    series = str(tmp_path / "env_metrics.jsonl")
    monkeypatch.setenv("STPU_METRICS_TO", series)
    monkeypatch.setenv("STPU_METRICS_EVERY", "1")
    c = _spawn().join()
    assert c._recorder is not None and c._recorder.path == series
    assert read_series(series)


# --- dispatch_log contract ------------------------------------------------


def _check_dispatch_log_shape(log):
    for entry in log:
        assert isinstance(entry, tuple) and len(entry) == 2, entry
        cap, committed = entry
        assert isinstance(cap, int) and cap > 0
        assert isinstance(committed, int) and committed >= 0


def test_dispatch_log_contract_single_vs_fused():
    # ONE documented shape on both dispatch paths (xla.py): one
    # (run_cap, committed_levels) per device call; the one-level path is
    # the committed∈{0,1} special case; on both, committed levels sum to
    # the level log.
    single = _spawn(levels_per_dispatch=1).join()
    fused = _spawn().join()
    for c in (single, fused):
        _check_dispatch_log_shape(c.dispatch_log)
        assert sum(n for _, n in c.dispatch_log) == len(c.level_log)
    assert all(n in (0, 1) for _, n in single.dispatch_log)
    assert any(n > 1 for _, n in fused.dispatch_log)


def test_dispatch_log_records_uncommitted_dispatches():
    # A frontier capacity below the space's peak width forces
    # grow-and-retry rounds. On the one-level path the overflowed level's
    # device call is a committed == 0 entry; a fused block instead
    # commits the pre-overflow prefix (possibly > 0) and re-enters. Both
    # keep the sum invariant.
    # Fresh models here, NOT the shared one: the jump ladder prefers an
    # already-compiled larger bucket, and the shared model's program
    # cache would let the run sidestep the forced overflow entirely.
    single = PackedTwoPhaseSys(3).checker().spawn_xla(
        frontier_capacity=16, table_capacity=1 << 13,
        levels_per_dispatch=1,
    ).join()
    assert single.unique_state_count() == 288
    _check_dispatch_log_shape(single.dispatch_log)
    assert sum(n for _, n in single.dispatch_log) == len(single.level_log)
    assert any(n == 0 for _, n in single.dispatch_log)
    assert single.metrics()["frontier_grows"] >= 1

    # (The fused path's prefix-commit behavior under the same squeeze is
    # covered by the sum invariant asserted in every other test here —
    # not re-run with a second fresh model, which would cost another
    # cold-compile schedule on this 1-core box.)


# --- mesh engine ----------------------------------------------------------


def test_sharded_dispatch_log_metrics_and_heartbeat(tmp_path):
    from stateright_tpu.obs import read_series

    trace = str(tmp_path / "mesh.jsonl")
    hb = str(tmp_path / "mesh_hb.json")
    series = str(tmp_path / "mesh_metrics.jsonl")
    c = _spawn(
        mesh=default_mesh(), trace=trace, heartbeat=hb,
        metrics_to=series, metrics_every=1,
    ).join()
    assert c.unique_state_count() == 288
    _check_dispatch_log_shape(c.dispatch_log)
    m = c.metrics()
    # Same stable key set as the single-chip engine, plus mesh gauges.
    assert METRIC_KEYS <= set(m), METRIC_KEYS - set(m)
    assert m["engine"] == "xla-sharded"
    assert m["shards"] == 8 and "route_grows" in m
    disp = [r for r in _spans(trace) if r["name"] == "dispatch"]
    assert len(disp) == len(c.dispatch_log)
    assert hb_mod.read(hb)["seq"] == len(c.dispatch_log)
    # The mesh engine records the same time-series contract: full
    # snapshots at quiescent boundaries only.
    rows = read_series(series)
    assert rows and all(set(r) == RECORDER_ROW_KEYS for r in rows)
    assert len(rows) <= len(c.dispatch_log)
    assert rows[-1]["metrics"]["engine"] == "xla-sharded"


# --- zero overhead when off ----------------------------------------------


def test_tracing_off_is_nulled_and_bit_identical(tmp_path):
    from stateright_tpu.obs.trace import NULL_TRACER

    off = _spawn().join()
    # No obs machinery on the hot path: the shared no-op tracer (no
    # clocks, no file), no heartbeat file, no metrics recorder — the
    # recorder shares the tracer's off-by-default pin discipline.
    assert off._tracer is NULL_TRACER
    assert off._heartbeat is None
    assert off._recorder is None
    # The dispatch-phase profiler shares the pin: off by default, no
    # clock stamps, no rows.
    assert off._phases is False and off.phase_log == []

    trace = str(tmp_path / "trace.jsonl")
    hb = str(tmp_path / "hb.json")
    on = _spawn(
        trace=trace, heartbeat=hb, phases=True,
        metrics_to=str(tmp_path / "metrics.jsonl"), metrics_every=1,
    ).join()
    assert len(on.phase_log) == len(on.dispatch_log) > 0
    # Engine results are bit-identical with tracing on: same counts, same
    # schedule, same per-level telemetry (spans only *observe* host
    # boundaries; they never change what runs on the device).
    assert (off.state_count(), off.unique_state_count(), off.max_depth()) == (
        on.state_count(), on.unique_state_count(), on.max_depth(),
    )
    assert off.level_log == on.level_log
    assert off.dispatch_log == on.dispatch_log
    assert {n: p.into_actions() for n, p in off.discoveries().items()} == {
        n: p.into_actions() for n, p in on.discoveries().items()
    }
