"""Packed timers (Pingers) on the device engine vs the object model.

The space is unbounded, so parity uses ``target_max_depth``: BFS-to-depth-d
visits an exploration-order-independent state set in both engines, making
unique counts exactly comparable. The codec roundtrip is checked
state-by-state over the depth-bounded reachable set (pack/unpack must
reproduce the object state exactly — actor counters, multiset counts, and
the constant {Even, Odd, NoOp} timer sets).
"""

from stateright_tpu.fingerprint import fingerprint
from stateright_tpu.models.timers import PackedTimers, timers_model

KW = dict(frontier_capacity=1 << 12, table_capacity=1 << 15)


def _reach_to_depth(model, depth):
    frontier = list(model.init_states())
    seen = {fingerprint(s): s for s in frontier}
    for _ in range(depth - 1):
        nxt = []
        for s in frontier:
            for _a, t in model.next_steps(s):
                fp = fingerprint(t)
                if fp not in seen:
                    seen[fp] = t
                    nxt.append(t)
        frontier = nxt
    return seen


def test_packed_timers_depth_parity():
    obj = timers_model(3).checker().target_max_depth(5).spawn_bfs().join()
    dev = PackedTimers(3).checker().target_max_depth(5).spawn_xla(**KW).join()
    assert dev.unique_state_count() == obj.unique_state_count()
    assert dev.max_depth() == obj.max_depth() == 5


def test_packed_timers_codec_roundtrip():
    packed = PackedTimers(3)
    obj = timers_model(3)
    seen = _reach_to_depth(obj, 4)
    assert len(seen) > 50
    for fp, state in seen.items():
        words = packed.pack(state)
        back = packed.unpack(words)
        assert back == state
        assert fingerprint(back) == fp


def test_packed_timers_noop_suppression():
    # Actor 1 has no odd peers (peers are 0 and 2), so its Odd timeout is a
    # pure re-arm — suppressed in the object model and statically invalid
    # in the packed grid; NoOp never gets a slot at all. Depth parity above
    # would fail if either engine generated those states, but check the
    # static grid directly too.
    import numpy as np

    import jax.numpy as jnp

    packed = PackedTimers(3)
    init = jnp.asarray(packed.packed_init()[0])
    nxt, valid, ovf = packed.packed_step(init)
    valid = np.asarray(valid)
    # Slots: per actor [Even, Odd] then deliveries (all empty at init).
    # Actor 1's Odd slot (index 3) is statically invalid.
    assert valid[:6].tolist() == [True, True, True, False, True, True]
    assert not valid[6:].any()  # no deliverable envelopes at init
