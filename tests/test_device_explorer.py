"""The Explorer backed by the DEVICE engine (VERDICT round-2 missing #4).

The reference Explorer wraps its real engine (explorer.rs:81-103); here
``serve()``/``make_app()`` on a packed model route to
``DeviceOnDemandChecker``: every expansion is a compiled super-step against
the device hash set, and ``run_to_completion`` hands over to the fused
batch engine. The host oracle never expands a state (its engine is not even
constructed)."""

import numpy as np

from stateright_tpu.checker.device_on_demand import DeviceOnDemandChecker
from stateright_tpu.checker.explorer import make_app
from stateright_tpu.checker.on_demand import OnDemandChecker
from stateright_tpu.models.two_phase_commit import PackedTwoPhaseSys, TwoPhaseSys

KW = dict(frontier_capacity=1 << 10, table_capacity=1 << 12)


def test_auto_engine_selection():
    _, dev = make_app(PackedTwoPhaseSys(3).checker(), **KW)
    assert isinstance(dev, DeviceOnDemandChecker)
    _, host = make_app(TwoPhaseSys(3).checker())
    assert isinstance(host, OnDemandChecker)
    _, forced = make_app(PackedTwoPhaseSys(3).checker(), engine="host")
    assert isinstance(forced, OnDemandChecker)


def test_click_through_matches_host_explorer():
    # Same click sequence on both backends: identical views (fingerprints,
    # state renderings, action labels) and identical count trajectories.
    dev_app, dev = make_app(PackedTwoPhaseSys(3).checker(), **KW)
    host_app, host = make_app(TwoPhaseSys(3).checker(), engine="host")

    code_d, init_d = dev_app.states("/")
    code_h, init_h = host_app.states("/")
    assert code_d == code_h == 200
    assert [v["fingerprint"] for v in init_d] == [v["fingerprint"] for v in init_h]
    assert [v["state"] for v in init_d] == [v["state"] for v in init_h]

    path = "/" + init_d[0]["fingerprint"]
    code_d, ch_d = dev_app.states(path)
    code_h, ch_h = host_app.states(path)
    assert code_d == code_h == 200
    assert [v.get("fingerprint") for v in ch_d] == [v.get("fingerprint") for v in ch_h]
    assert [v.get("action") for v in ch_d] == [v.get("action") for v in ch_h]
    assert (dev.state_count(), dev.unique_state_count()) == (
        host.state_count(),
        host.unique_state_count(),
    )

    # Deeper click: counts keep tracking the host engine exactly.
    deeper = path + "/" + ch_d[0]["fingerprint"]
    assert dev_app.states(deeper)[0] == 200
    assert host_app.states(deeper)[0] == 200
    assert (dev.state_count(), dev.unique_state_count()) == (
        host.state_count(),
        host.unique_state_count(),
    )


def test_unknown_path_404():
    app, _ = make_app(PackedTwoPhaseSys(3).checker(), **KW)
    code, msg = app.states("/notanumber")
    assert code == 404
    code, msg = app.states("/12345")  # unreachable fingerprint
    assert code == 404


def test_run_to_completion_uses_fused_batch_engine():
    app, checker = make_app(PackedTwoPhaseSys(3).checker(), **KW)
    code, inits = app.states("/")
    assert code == 200
    app.states("/" + inits[0]["fingerprint"])  # partial interactive progress
    app.run_to_completion()
    while not checker.is_done():
        app.drive()
    st = app.status()
    assert st["done"]
    assert (st["state_count"], st["unique_state_count"]) == (1146, 288)
    # Witness paths for both sometimes-properties, reconstructed from the
    # device parent table, encoded for the UI.
    props = {name: enc for _, name, enc in st["properties"]}
    assert props["commit agreement"] and props["abort agreement"]


def test_device_explorer_live_socket_smoke():
    """One real HTTP round-trip against the DEVICE backend: status, init
    states, a click (device super-step expansion), and run-to-completion —
    the browser contract end-to-end with the packed engine underneath."""
    import json
    import threading
    import urllib.request
    from http.server import ThreadingHTTPServer

    from stateright_tpu.checker.explorer import _ExplorerHandler

    app, checker = make_app(PackedTwoPhaseSys(3).checker(), **KW)
    assert isinstance(checker, DeviceOnDemandChecker)

    class Handler(_ExplorerHandler):
        explorer_app = app

    server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    port = server.server_address[1]
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()

    def get(path):
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=30
        ) as resp:
            return json.load(resp)

    try:
        status = get("/.status")
        assert status["model"] == "PackedTwoPhaseSys"
        inits = get("/.states/")
        assert len(inits) == 1
        children = get("/.states/" + inits[0]["fingerprint"])
        assert sum("state" in v for v in children) == 7
        assert get("/.status")["unique_state_count"] > 1
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/.runtocompletion", method="POST"
        )
        with urllib.request.urlopen(req, timeout=30):
            pass
        while not checker.is_done():
            app.drive()
        final = get("/.status")
        assert final["done"] and final["unique_state_count"] == 288
    finally:
        server.shutdown()
        t.join(timeout=5)


def test_join_before_unblock_raises():
    import pytest

    _, checker = make_app(PackedTwoPhaseSys(3).checker(), **KW)
    checker.check_state(next(iter(checker.model().init_states())))
    with pytest.raises(RuntimeError, match="run_to_completion"):
        checker.join()


def test_device_on_demand_sorted_dedup_parity():
    """The demand-driven device checker over the sorted structure (what a
    TPU-backed Explorer runs): click-for-click results match the hash
    structure's."""
    from stateright_tpu.models.two_phase_commit import PackedTwoPhaseSys

    out = {}
    for dedup in ("hash", "sorted"):
        m = PackedTwoPhaseSys(3)
        c = m.checker().spawn_on_demand(
            engine="xla",
            dedup=dedup,
            frontier_capacity=1 << 8,
            table_capacity=1 << 10,
        )
        init = m.init_states()[0]
        c.check_state(init)
        lvl1 = sorted(c._pool)  # pending children after one click
        c.run_to_completion()
        c.join()
        out[dedup] = (lvl1, c.state_count(), c.unique_state_count(), c.max_depth())
    assert out["hash"] == out["sorted"]
    assert out["hash"][2] == 288
