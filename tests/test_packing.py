"""Packing toolkit: layouts, slot multisets, FIFO lanes, bounded history.

The toolkit is the generic replacement for per-model bit twiddling (VERDICT
round 1, missing #3); these tests pin its contracts: host/device round
trips, canonical (order-insensitive) packing, loud overflow, and exact
conversion to/from the live consistency testers.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from stateright_tpu.packing import (
    BoundedHistory,
    FifoLanes,
    LayoutBuilder,
    OverflowError32,
    SlotMultiset,
)


# --- Layout ----------------------------------------------------------------


def test_layout_pack_unpack_round_trip():
    lay = (
        LayoutBuilder()
        .uint("a", 4)
        .uint("b", 7)
        .flag("c")
        .array("xs", 5, 6)
        .uint("d", 32)
        .finish()
    )
    vals = dict(a=9, b=101, c=1, xs=[1, 2, 3, 62, 63], d=0xDEADBEEF)
    words = lay.pack(**vals)
    assert lay.unpack(words) == vals


def test_layout_fields_do_not_span_words():
    lay = LayoutBuilder().uint("a", 20).uint("b", 20).finish()
    fa, fb = lay.fields["a"], lay.fields["b"]
    assert fa.word != fb.word  # 20+20 > 32: b starts a fresh word
    assert fb.shift == 0


def test_layout_pack_overflow_is_loud():
    lay = LayoutBuilder().uint("a", 3).finish()
    with pytest.raises(OverflowError32):
        lay.pack(a=8)


def test_layout_device_get_set_matches_host():
    lay = LayoutBuilder().uint("a", 5).array("xs", 7, 9).finish()
    words = jnp.asarray(lay.pack(a=17, xs=[3, 1, 4, 1, 5, 9, 2]))

    @jax.jit
    def bump(words, i):
        v = lay.get(words, "xs", i)
        return lay.set(words, "xs", v + 1, i)

    for i in [0, 3, 6]:
        words = bump(words, i)
    got = lay.unpack(np.asarray(words))
    assert got["xs"] == [4, 1, 4, 2, 5, 9, 3]
    assert got["a"] == 17


def test_layout_device_set_traced_index():
    lay = LayoutBuilder().array("xs", 6, 8).finish()
    words = jnp.asarray(lay.pack(xs=[0] * 6))

    @jax.jit
    def fill(words):
        def body(i, w):
            return lay.set(w, "xs", i * 10, i)

        return jax.lax.fori_loop(0, 6, body, words)

    got = lay.unpack(np.asarray(fill(words)))
    assert got["xs"] == [0, 10, 20, 30, 40, 50]


# --- SlotMultiset ----------------------------------------------------------


def _multiset_fixture(k=4, code_bits=8, count_bits=2):
    b = LayoutBuilder().uint("other", 5).words("net", k)
    lay = b.finish()
    ms = SlotMultiset(lay, "net", code_bits, count_bits)
    return lay, ms


def test_multiset_host_pack_is_canonical():
    lay, ms = _multiset_fixture()
    a = ms.host_pack([(7, 2), (3, 1)])
    b = ms.host_pack([(3, 1), (7, 2)])
    assert a == b  # order-insensitive: sorted slots
    assert ms.host_unpack(a) == [(3, 1), (7, 2)]


def test_multiset_host_overflow_loud():
    lay, ms = _multiset_fixture(k=2)
    with pytest.raises(OverflowError32):
        ms.host_pack([(1, 1), (2, 1), (3, 1)])  # too many distinct codes
    with pytest.raises(OverflowError32):
        ms.host_pack([(1, 5)])  # count > 2**count_bits
    with pytest.raises(OverflowError32):
        ms.host_pack([(256, 1)])  # code too wide


def test_multiset_device_send_deliver_round_trip():
    lay, ms = _multiset_fixture()
    words0 = jnp.asarray(lay.pack(other=9, net=ms.host_pack([(3, 1)])))

    @jax.jit
    def step(words):
        words, ovf1 = ms.send(words, jnp.uint32(7))
        words, ovf2 = ms.send(words, jnp.uint32(3))  # bump existing
        return words, ovf1 | ovf2

    words, ovf = step(words0)
    assert not bool(ovf)
    assert ms.host_unpack(np.asarray(words)[1:]) == [(3, 2), (7, 1)]
    assert lay.unpack(np.asarray(words))["other"] == 9

    # Deliver one instance of code 3 (its slot index after canonical sort).
    slots = list(np.asarray(words)[1:])
    i3 = next(i for i, s in enumerate(slots) if s and (int(s) >> 2) - 1 == 3)
    words2 = jax.jit(lambda w: ms.remove_slot(w, i3))(words)
    assert ms.host_unpack(np.asarray(words2)[1:]) == [(3, 1), (7, 1)]


def test_multiset_device_overflow_flags():
    lay, ms = _multiset_fixture(k=2, count_bits=1)
    words = jnp.asarray(lay.pack(net=ms.host_pack([(1, 2), (2, 1)])))
    # count saturated for code 1 (max_count = 2)
    w2, ovf = jax.jit(lambda w: ms.send(w, jnp.uint32(1)))(words)
    assert bool(ovf)
    # ...and the slots are NOT corrupted: the +1 must not carry into the
    # code bits (a saturated send leaves the multiset unchanged).
    assert ms.host_unpack(np.asarray(w2)) == [(1, 2), (2, 1)]
    # no free slot for a new code
    _, ovf = jax.jit(lambda w: ms.send(w, jnp.uint32(9)))(words)
    assert bool(ovf)
    # disabled send never overflows
    _, ovf = jax.jit(lambda w: ms.send(w, jnp.uint32(9), enabled=False))(words)
    assert not bool(ovf)


def test_multiset_duplicating_set_semantics():
    b = LayoutBuilder().words("net", 3)
    lay = b.finish()
    ms = SlotMultiset(lay, "net", code_bits=8, count_bits=0)
    words = jnp.asarray(lay.pack(net=ms.host_pack([(5, 1)])))
    # Re-sending a present code is a no-op (sets, not multisets).
    words, ovf = jax.jit(lambda w: ms.send(w, jnp.uint32(5)))(words)
    assert not bool(ovf)
    assert ms.host_unpack(np.asarray(words)) == [(5, 1)]
    # remove drops the envelope entirely.
    slots = list(np.asarray(words))
    i5 = next(i for i, s in enumerate(slots) if s)
    words = jax.jit(lambda w: ms.remove_slot(w, i5))(words)
    assert ms.host_unpack(np.asarray(words)) == []


def test_multiset_differential_vs_object_network():
    """Random op sequences against UnorderedNonDuplicatingNetwork."""
    from stateright_tpu.actor import Id
    from stateright_tpu.actor.network import Envelope, Network

    # count_bits=6 (cap 64) so the uncapped object multiset can't outrun it.
    k, code_bits, count_bits = 16, 6, 6
    b = LayoutBuilder().words("net", k)
    lay = b.finish()
    ms = SlotMultiset(lay, "net", code_bits, count_bits)

    rng = np.random.default_rng(7)
    net = Network.new_unordered_nonduplicating()
    code_of = {}  # env -> code

    def env_for(code):
        return Envelope(Id(code % 3), Id(code // 3 % 3), ("m", code))

    words = jnp.asarray(lay.pack())
    send = jax.jit(lambda w, c: ms.send(w, c))
    rm = jax.jit(lambda w, i: ms.remove_slot(w, i), static_argnums=1)
    for _ in range(60):
        present = ms.host_unpack(np.asarray(words))
        if present and rng.random() < 0.4:
            code, _cnt = present[rng.integers(len(present))]
            slots = list(np.asarray(words))
            i = next(
                j for j, s in enumerate(slots) if s and (int(s) >> count_bits) - 1 == code
            )
            words = rm(words, i)
            net = net.on_deliver(env_for(code))
        else:
            code = int(rng.integers(0, 12))
            words, ovf = send(words, jnp.uint32(code))
            assert not bool(ovf)
            net = net.send(env_for(code))
        # Same multiset content both sides.
        got = {env_for(c): n for c, n in ms.host_unpack(np.asarray(words))}
        assert got == net.counts


# --- FifoLanes -------------------------------------------------------------


def test_fifo_push_pop_fifo_order():
    b = LayoutBuilder().uint("x", 3)
    lanes = FifoLanes(b, "flows", lanes=2, depth=3, code_bits=5)
    lay = b.finish()
    lanes.bind(lay)
    words = jnp.asarray(lay.pack(x=5))

    @jax.jit
    def run(words):
        words, o1 = lanes.push(words, 0, jnp.uint32(10))
        words, o2 = lanes.push(words, 0, jnp.uint32(11))
        words, o3 = lanes.push(words, 1, jnp.uint32(29))
        return words, o1 | o2 | o3

    words, ovf = run(words)
    assert not bool(ovf)
    code, ok = jax.jit(lambda w: lanes.head(w, 0))(words)
    assert bool(ok) and int(code) == 10
    words = jax.jit(lambda w: lanes.pop(w, 0))(words)
    code, ok = jax.jit(lambda w: lanes.head(w, 0))(words)
    assert bool(ok) and int(code) == 11
    code, ok = jax.jit(lambda w: lanes.head(w, 1))(words)
    assert bool(ok) and int(code) == 29
    assert lay.unpack(np.asarray(words))["x"] == 5


def test_fifo_overflow_and_empty_pop():
    b = LayoutBuilder()
    lanes = FifoLanes(b, "flows", lanes=1, depth=2, code_bits=4)
    lay = b.finish()
    lanes.bind(lay)
    words = jnp.asarray(lay.pack())
    push = jax.jit(lambda w, c: lanes.push(w, 0, c))
    words, ovf = push(words, jnp.uint32(1))
    words, ovf = push(words, jnp.uint32(2))
    assert not bool(ovf)
    _, ovf = push(words, jnp.uint32(3))
    assert bool(ovf)  # depth exceeded, loudly
    # pop on empty lane is a no-op
    empty = jnp.asarray(lay.pack())
    same = jax.jit(lambda w: lanes.pop(w, 0))(empty)
    np.testing.assert_array_equal(np.asarray(empty), np.asarray(same))


# --- BoundedHistory --------------------------------------------------------


def _reg_codecs():
    from stateright_tpu.semantics.register import (
        Read,
        ReadOk,
        Write,
        WriteOk,
    )

    values = [None, "A", "B"]

    def op_code(op):
        return 0 if isinstance(op, Read) else 1 + values.index(op.value)

    def code_op(c):
        return Read() if c == 0 else Write(values[c - 1])

    def ret_code(ret):
        return 0 if isinstance(ret, WriteOk) else 1 + values.index(ret.value)

    def code_ret(c):
        return WriteOk() if c == 0 else ReadOk(values[c - 1])

    return op_code, code_op, ret_code, code_ret


def _make_tester():
    from stateright_tpu.semantics import LinearizabilityTester
    from stateright_tpu.semantics.register import Register

    return LinearizabilityTester(Register(None))


def test_bounded_history_tester_round_trip():
    from stateright_tpu.semantics.register import Read, ReadOk, Write, WriteOk

    op_code, code_op, ret_code, code_ret = _reg_codecs()
    b = LayoutBuilder()
    hist = BoundedHistory(b, thread_ids=[3, 4], max_ops=2, op_bits=3, ret_bits=3)
    lay = b.finish()
    hist.bind(lay)

    t = _make_tester()
    t.on_invoke(3, Write("A"))
    t.on_invoke(4, Write("B"))
    t.on_return(3, WriteOk())
    t.on_invoke(3, Read())
    t.on_return(4, WriteOk())
    t.on_return(3, ReadOk("A"))

    words = lay.pack(**hist.from_tester(t, op_code, ret_code))
    rebuilt = hist.to_tester(lay.unpack(words), _make_tester, code_op, code_ret)
    assert rebuilt == t  # exact value equality incl. prereq snapshots
    assert rebuilt.__fingerprint_key__() == t.__fingerprint_key__()
    assert rebuilt.serialized_history() == t.serialized_history()


def test_bounded_history_device_matches_object_tester():
    """Replaying invoke/return on device produces the identical packed
    words as packing the object tester after the same calls."""
    from stateright_tpu.semantics.register import Read, ReadOk, Write, WriteOk

    op_code, code_op, ret_code, code_ret = _reg_codecs()
    b = LayoutBuilder()
    hist = BoundedHistory(b, thread_ids=[3, 4], max_ops=2, op_bits=3, ret_bits=3)
    lay = b.finish()
    hist.bind(lay)

    script = [
        ("invoke", 3, Write("A")),
        ("invoke", 4, Write("B")),
        ("return", 3, WriteOk()),
        ("invoke", 3, Read()),
        ("return", 4, WriteOk()),
        ("return", 3, ReadOk("A")),
    ]

    t = _make_tester()
    words = jnp.asarray(hist.init_words(jnp.asarray(lay.pack())))
    for kind, tid, obj in script:
        tpos = hist.thread_ids.index(tid)
        if kind == "invoke":
            t.on_invoke(tid, obj)
            words = jax.jit(
                lambda w, c, _t=tpos: hist.on_invoke(w, _t, c)
            )(words, jnp.uint32(op_code(obj)))
        else:
            t.on_return(tid, obj)
            words, hovf = jax.jit(
                lambda w, c, _t=tpos: hist.on_return(w, _t, c)
            )(words, jnp.uint32(ret_code(obj)))
            assert not bool(hovf)
        expect = lay.pack(**hist.from_tester(t, op_code, ret_code))
        np.testing.assert_array_equal(np.asarray(words), expect)
    rebuilt = hist.to_tester(lay.unpack(np.asarray(words)), _make_tester, code_op, code_ret)
    assert rebuilt == t


def test_bounded_history_device_overflow_and_poison():
    from stateright_tpu.semantics.register import Write

    op_code, code_op, ret_code, code_ret = _reg_codecs()
    b = LayoutBuilder()
    hist = BoundedHistory(b, thread_ids=[0, 1], max_ops=1, op_bits=3, ret_bits=3)
    lay = b.finish()
    hist.bind(lay)
    words = jnp.asarray(hist.init_words(jnp.asarray(lay.pack())))
    invoke = jax.jit(lambda w, c: hist.on_invoke(w, 0, c))
    ret = jax.jit(lambda w, c: hist.on_return(w, 0, c))
    # First op completes fine.
    words = invoke(words, jnp.uint32(1))
    words, ovf = ret(words, jnp.uint32(0))
    assert not bool(ovf)
    # Second completed op exceeds max_ops=1: loud overflow, not silence.
    words = invoke(words, jnp.uint32(2))
    words, ovf = ret(words, jnp.uint32(0))
    assert bool(ovf)
    # Return with nothing in flight poisons h_valid (tester HistoryError).
    fresh = jnp.asarray(hist.init_words(jnp.asarray(lay.pack())))
    fresh, ovf2 = ret(fresh, jnp.uint32(0))
    assert not bool(ovf2)
    assert lay.unpack(np.asarray(fresh))["h_valid"] == 0
    # Invoke while in flight poisons too.
    w = jnp.asarray(hist.init_words(jnp.asarray(lay.pack())))
    w = invoke(w, jnp.uint32(1))
    w = invoke(w, jnp.uint32(2))
    assert lay.unpack(np.asarray(w))["h_valid"] == 0


def test_bounded_history_overflow_loud():
    op_code, code_op, ret_code, code_ret = _reg_codecs()
    from stateright_tpu.semantics.register import Write, WriteOk

    b = LayoutBuilder()
    hist = BoundedHistory(b, thread_ids=[0, 1], max_ops=1, op_bits=3, ret_bits=3)
    lay = b.finish()
    hist.bind(lay)
    t = _make_tester()
    for _ in range(2):
        t.on_invoke(0, Write("A"))
        t.on_return(0, WriteOk())
    with pytest.raises(OverflowError32):
        hist.from_tester(t, op_code, ret_code)


# --- scatter-free traced-index writes --------------------------------------
#
# These two tests are thin regression shims over the stpu-lint STPU001
# pass (stateright_tpu/analysis): the one-off HLO regex pin they used to
# carry is generalized there into the jaxpr-level data-dependent-scatter
# scan that sweeps ALL seven packed models x both engines
# (tests/test_analysis.py, tools/smoke.sh's lint stage). Kept here: the
# bit-exactness halves (the analyzer never executes anything) plus one
# call into the shared pass per body, so packing regressions still fail
# in THIS file next to the codec they break.


def _assert_stpu001_clean(body, *args):
    from stateright_tpu.analysis.jaxpr_lint import taint_scatters

    jx = jax.make_jaxpr(jax.vmap(body))(*args)
    hits = taint_scatters(jx, "test:packing")
    assert not hits, "traced-index write lowered to a data-dependent scatter:\n" + (
        "\n".join(f.format() for f in hits)
    )


def test_word_update_is_scatter_free_and_exact(monkeypatch):
    """Traced-index field writes must go through the one-hot lowering
    (packing._word_update) on accelerators: XLA:TPU silently drops
    data-dependent one-element scatters inside vmapped model kernels at
    batch >= 4096 (round-5 on-chip paxos drift; bisection in
    tools/paxos_diag.py). Pins (a) bit-exactness of Layout.set /
    SlotMultiset under traced indices against the host pack() oracle,
    and (b) scatter-freedom of the vmapped field-writing body under the
    accelerator lowering (forced via packing.ONE_HOT_WRITES — the CPU
    backend keeps the O(1) scatter, which is correct there), via the
    stpu-lint STPU001 pass."""
    import stateright_tpu.packing as packing

    monkeypatch.setattr(packing, "ONE_HOT_WRITES", True)
    lay = (
        LayoutBuilder()
        .array("bits", 40, 1)
        .array("vals", 6, 4)
        .uint("w32", 32)
        .finish()
    )

    def body(words, i):
        words = lay.set(words, "bits", 1, i * 3)
        words = lay.set(words, "vals", i % 6, i % 6)
        return lay.set(words, "w32", i * 0x1010101)

    n = 13
    base = jnp.asarray(np.tile(lay.pack(), (n, 1)))
    out = np.asarray(
        jax.jit(jax.vmap(body))(base, jnp.arange(n, dtype=jnp.uint32))
    )
    for i in range(n):
        f = lay.unpack(out[i])
        assert f["bits"][i * 3] == 1
        assert f["vals"][i % 6] == i % 6
        assert f["w32"] == i * 0x1010101

    _assert_stpu001_clean(body, base, jnp.arange(n, dtype=jnp.uint32))


def test_slot_multiset_send_remove_scatter_free(monkeypatch):
    import stateright_tpu.packing as packing

    monkeypatch.setattr(packing, "ONE_HOT_WRITES", True)
    b = LayoutBuilder()
    b.words("net", 4)
    lay = b.finish()
    ms = SlotMultiset(lay, "net", code_bits=8, count_bits=2)

    def body(words, code):
        words, ovf = ms.send(words, code)
        words, _ = ms.send(words, code + jnp.uint32(1))
        return ms.remove_slot(words, jnp.int32(3)), ovf

    base = jnp.asarray(np.tile(lay.pack(), (5, 1)))
    codes = jnp.arange(5, dtype=jnp.uint32) * 7
    (out, ovf) = jax.jit(jax.vmap(body))(base, codes)
    assert not bool(np.any(np.asarray(ovf)))
    for i in range(5):
        # send(c), send(c+1), then remove the top slot (c+1 — slots sort
        # ascending with empties first) leaves exactly {c}.
        assert ms.host_unpack(np.asarray(out)[i][lay.fields["net"].word :]) == [
            (i * 7, 1)
        ]
    _assert_stpu001_clean(body, base, codes)
