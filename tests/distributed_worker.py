"""Worker body for the two-process distributed-mesh test.

Each process contributes 4 virtual CPU devices to an 8-device global mesh
(`jax.distributed` over localhost — the DCN path of SURVEY §2.8), runs the
SAME sharded check SPMD-style, and prints one RESULT line. The reference's
checker is shared-memory only (bfs.rs:89-93); this is the scale-out path it
doesn't have.

Usage: distributed_worker.py <process_id> <num_processes> <coordinator_port> [config]

Configs (the round-3 verdict asked the process boundary to be evidenced
beyond one configuration):

- ``2pc`` (default): 2pc(3), engine-default visited structure, the
  checkpoint-allgather probe, and SOMETIMES witness reconstruction.
- ``2pc-sorted`` / ``2pc-delta``: the same check on the sort-merge and
  two-tier delta structures (the delta config starts at a table small
  enough to force flush cycles and growth across the process boundary).
- ``ev``: a DGraph cycle with an EVENTUALLY property — terminal-detection
  semantics plus reconstruction of the eventually-counterexample path
  across non-addressable parent-map shards.
- ``hv``: the host-verified-property path across the process boundary —
  the single-copy register's linearizability forced through the
  conservative-predicate machinery, with the stale-read counterexample
  confirmed on host from candidate buffers allgathered over the DCN
  transport (``_host_read`` on arrays spanning non-addressable shards).
"""

import os
import sys


def main() -> None:
    pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    config = sys.argv[4] if len(sys.argv) > 4 else "2pc"
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=4"
    )
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=nproc,
        process_id=pid,
    )
    assert jax.process_count() == nproc, jax.process_count()
    assert len(jax.local_devices()) == 4
    assert len(jax.devices()) == 4 * nproc

    import numpy as np
    from jax.sharding import Mesh

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

    mesh = Mesh(np.asarray(jax.devices()), ("shards",))
    kwargs = dict(frontier_capacity=1 << 9, table_capacity=1 << 12)
    if config in ("2pc", "2pc-sorted", "2pc-delta"):
        from stateright_tpu.models.two_phase_commit import PackedTwoPhaseSys

        if config == "2pc-sorted":
            kwargs["dedup"] = "sorted"
        elif config == "2pc-delta":
            # Small table: the delta tier flushes repeatedly and the main
            # tier grows, all across the process boundary.
            kwargs.update(dedup="delta", table_capacity=1 << 9)
        builder = PackedTwoPhaseSys(3).checker()
    elif config == "hv":
        from stateright_tpu.models.single_copy_register import (
            PackedSingleCopyRegister,
        )

        builder = PackedSingleCopyRegister(2, 2, device_exact=False).checker()
    elif config == "ev":
        from stateright_tpu.core import Property
        from stateright_tpu.test_util import DGraph, PackedDGraph

        # A cycle that never reaches an odd node: the EVENTUALLY property
        # must surface a terminal/cycle counterexample (the documented
        # cycle false-negative semantics are the single-chip engine's; the
        # mesh must reproduce them bit-for-bit).
        graph = (
            DGraph.with_property(
                Property.eventually("odd", lambda _, s: s % 2 == 1)
            )
            .with_path([0, 2, 4])
            .with_path([4, 6])
        )
        builder = PackedDGraph(graph).checker()
    else:  # pragma: no cover - driver error
        raise SystemExit(f"unknown config {config!r}")

    checker = builder.spawn_xla(mesh=mesh, **kwargs).join()
    # discoveries() gathers table planes across processes (a collective:
    # every process must reach it, SPMD-style) and rebuilds witness paths.
    paths = ";".join(
        f"{name}:{len(path)}" for name, path in sorted(checker.discoveries().items())
    )
    if config == "2pc":
        # Checkpointing allgathers the same planes; every process saves
        # (the allgather is a collective) to its own path, and the payload
        # must describe the GLOBAL search state on each.
        import tempfile

        from stateright_tpu.checkpoint import load_checkpoint

        ckpt = os.path.join(tempfile.gettempdir(), f"dw_ckpt_{os.getpid()}.npz")
        checker.save_checkpoint(ckpt)
        ck = load_checkpoint(ckpt)
        os.unlink(ckpt)
        assert ck["meta"]["unique_count"] == checker.unique_state_count()
        assert len(ck["key_hi"]) == checker.unique_state_count()
    # The visited planes must be duplicate-free and sized exactly to the
    # committed unique count on EVERY process (stateright_tpu/audit.py).
    from stateright_tpu.audit import audit_table

    report = audit_table(checker)
    assert report["ok"], report
    print(
        f"RESULT pid={pid} states={checker.state_count()} "
        f"unique={checker.unique_state_count()} depth={checker.max_depth()} "
        f"paths={paths}",
        flush=True,
    )


if __name__ == "__main__":
    main()
