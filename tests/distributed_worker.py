"""Worker body for the two-process distributed-mesh test.

Each process contributes 4 virtual CPU devices to an 8-device global mesh
(`jax.distributed` over localhost — the DCN path of SURVEY §2.8), runs the
SAME sharded check SPMD-style, and prints one RESULT line. The reference's
checker is shared-memory only (bfs.rs:89-93); this is the scale-out path it
doesn't have.

Usage: distributed_worker.py <process_id> <num_processes> <coordinator_port>
"""

import os
import sys


def main() -> None:
    pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=4"
    )
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=nproc,
        process_id=pid,
    )
    assert jax.process_count() == nproc, jax.process_count()
    assert len(jax.local_devices()) == 4
    assert len(jax.devices()) == 4 * nproc

    import numpy as np
    from jax.sharding import Mesh

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from stateright_tpu.models.two_phase_commit import PackedTwoPhaseSys

    mesh = Mesh(np.asarray(jax.devices()), ("shards",))
    checker = (
        PackedTwoPhaseSys(3)
        .checker()
        .spawn_xla(mesh=mesh, frontier_capacity=1 << 9, table_capacity=1 << 12)
        .join()
    )
    # discoveries() gathers table planes across processes (a collective:
    # every process must reach it, SPMD-style) and rebuilds witness paths.
    paths = ";".join(
        f"{name}:{len(path)}" for name, path in sorted(checker.discoveries().items())
    )
    # Checkpointing allgathers the same planes; every process saves (the
    # allgather is a collective) to its own path, and the payload must
    # describe the GLOBAL search state on each.
    import tempfile

    from stateright_tpu.checkpoint import load_checkpoint

    ckpt = os.path.join(tempfile.gettempdir(), f"dw_ckpt_{os.getpid()}.npz")
    checker.save_checkpoint(ckpt)
    ck = load_checkpoint(ckpt)
    os.unlink(ckpt)
    assert ck["meta"]["unique_count"] == checker.unique_state_count()
    assert len(ck["key_hi"]) == checker.unique_state_count()
    print(
        f"RESULT pid={pid} states={checker.state_count()} "
        f"unique={checker.unique_state_count()} depth={checker.max_depth()} "
        f"paths={paths}",
        flush=True,
    )


if __name__ == "__main__":
    main()
