"""Live UDP ABD cluster: three quorum-register replicas + a driver client.

Same runtime-proof pattern as the live Paxos test: the model-checked
AbdActor binds real sockets, runs both ABD phases (quorum Query, quorum
Record) over loopback UDP, and serves a read whose value equals the write.
Writes and reads are driven at DIFFERENT replicas, so the read's quorum
must intersect the write's — the actual ABD property.
"""

import threading

from stateright_tpu.actor import Id
from stateright_tpu.actor import register as reg
from stateright_tpu.actor.spawn import json_codec, spawn
from stateright_tpu.models.linearizable_register import (
    AbdActor,
    AckQuery,
    AckRecord,
    Query,
    Record,
)


class Driver:
    """Put at one replica, then Get at another, with resend guards."""

    def __init__(self, put_at, get_at, record, done):
        self.put_at = put_at
        self.get_at = get_at
        self.record = record
        self.done = done

    def on_start(self, id, out):
        out.set_timer("kick", (0.05, 0.05))
        return "put"

    def on_timeout(self, id, state, timer, out):
        phase = state.get()
        if phase == "put":
            out.send(self.put_at, reg.Put(1, "X"))
        elif phase == "get":
            out.send(self.get_at, reg.Get(2))
        if phase != "done":
            out.set_timer("kick", (0.5, 0.5))

    def on_msg(self, id, state, src, msg, out):
        if isinstance(msg, reg.PutOk) and state.get() == "put":
            state.set("get")
            out.send(self.get_at, reg.Get(2))
        elif isinstance(msg, reg.GetOk) and state.get() == "get":
            self.record.append(msg.value)
            state.set("done")
            out.cancel_timer("kick")
            self.done.set()


def test_live_abd_cluster_read_sees_write_across_replicas():
    base = 28600
    ids = [Id.from_addr("127.0.0.1", base + i) for i in range(4)]
    servers, client = ids[:3], ids[3]
    serialize, deserialize = json_codec(
        reg.Put, reg.Get, reg.PutOk, reg.GetOk, reg.Internal,
        Query, AckQuery, Record, AckRecord,
    )
    record: list = []
    done = threading.Event()
    handles = spawn(
        serialize,
        deserialize,
        [(i, AbdActor([x for x in servers if x != i])) for i in servers]
        + [(client, Driver(servers[0], servers[2], record, done))],
        background=True,
    )
    try:
        assert done.wait(timeout=15), "ABD cluster failed to serve within 15s"
        assert record == ["X"]
    finally:
        for _thread, runtime in handles:
            runtime.stopped.set()
        for thread, _runtime in handles:
            thread.join(timeout=5)
