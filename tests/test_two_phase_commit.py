"""Two-phase commit oracle tests (reference: examples/2pc.rs:151-172)."""

import pytest

from stateright_tpu.models.two_phase_commit import (
    PackedTwoPhaseSys,
    TwoPhaseSys,
)


def test_can_model_2pc_bfs_rm3():
    checker = TwoPhaseSys(3).checker().spawn_bfs().join()
    assert checker.unique_state_count() == 288
    checker.assert_properties()


def test_can_model_2pc_dfs_rm5():
    checker = TwoPhaseSys(5).checker().spawn_dfs().join()
    assert checker.unique_state_count() == 8832
    checker.assert_properties()


def test_can_model_2pc_dfs_rm5_symmetry():
    checker = TwoPhaseSys(5).checker().symmetry().spawn_dfs().join()
    assert checker.unique_state_count() == 665
    checker.assert_properties()


def test_packed_codec_roundtrip():
    model = PackedTwoPhaseSys(3)
    # Walk the full object state space; pack/unpack must be the identity.
    seen = set()
    stack = list(model.init_states())
    while stack:
        s = stack.pop()
        if s in seen:
            continue
        seen.add(s)
        assert model.unpack(model.pack(s)) == s
        stack.extend(model.next_states(s))
    assert len(seen) == 288
