"""Enum-variant tuple semantics: the regression guard for the modeled
network's message identity.

Rust enum variants with identical payloads are never equal (derived
PartialEq/Hash include the discriminant); bare Python NamedTuples ARE equal
(`Accept(b,p) == Decided(b,p)`), which silently merged distinct messages in
the network multiset and corrupted state-space counts (caught by Paxos
parity: 19,816 states instead of the reference's 16,668).
"""

from stateright_tpu.fingerprint import fingerprint
from stateright_tpu.utils.variant import variant

A = variant("A", ["x", "y"])
B = variant("B", ["x", "y"])


def test_cross_class_inequality():
    assert A(1, 2) != B(1, 2)
    assert A(1, 2) != (1, 2)
    assert A(1, 2) == A(1, 2)
    assert A(1, 2) != A(1, 3)


def test_hash_and_fingerprint_distinguish_classes():
    assert hash(A(1, 2)) != hash(B(1, 2))
    assert fingerprint(A(1, 2)) != fingerprint(B(1, 2))
    assert fingerprint(A(1, 2)) == fingerprint(A(1, 2))
    # Sets/dicts keyed by messages keep variants separate.
    assert len({A(1, 2), B(1, 2)}) == 2
    assert len({A(1, 2): 1, B(1, 2): 1}) == 2


def test_namedtuple_conveniences_preserved():
    a = A(1, 2)
    assert a.x == 1 and a.y == 2
    assert a._replace(y=3) == A(1, 3)
    x, y = a
    assert (x, y) == (1, 2)
    assert repr(a) == "A(x=1, y=2)"


def test_same_name_different_module_fingerprints_differ():
    from stateright_tpu.actor.register import ClientState as RegClientState
    from stateright_tpu.actor.write_once_register import (
        ClientState as WOClientState,
    )

    assert RegClientState(None, 1) != WOClientState(None, 1)
    assert fingerprint(RegClientState(None, 1)) != fingerprint(WOClientState(None, 1))
