"""Live UDP runtime test: two actors on loopback sockets exchange a
timer-kicked ping-pong (VERDICT.md round-1 item #7).

The reference leaves ``spawn()`` untested beyond the Id/addr codec
(spawn.rs:204-220); this exercises the full loop — socket bind, timer
deadline scheduling, receive dispatch, command processing — end to end in
well under two seconds, with a deterministic outcome: every pong arrives
exactly once, in order.
"""

import socket
import threading
import time

from stateright_tpu.actor import Id
from stateright_tpu.actor.spawn import json_codec, spawn
from stateright_tpu.utils.variant import variant

Ping = variant("Ping", ["n"])
Pong = variant("Pong", ["n"])


class Ponger:
    """Echoes every Ping; counts handled messages in its state."""

    def on_start(self, id, out):
        return 0

    def on_msg(self, id, state, src, msg, out):
        if isinstance(msg, Ping):
            out.send(src, Pong(msg.n))
            state.set(state.get() + 1)

    def on_timeout(self, id, state, timer, out):
        pass


class Pinger:
    """Starts pinging on a timer (the deterministic timer-path exercise),
    resends the outstanding ping on a resend timer, and records pongs."""

    def __init__(self, target, count, record, done):
        self.target = target
        self.count = count
        self.record = record
        self.done = done

    def on_start(self, id, out):
        out.set_timer("kick", (0.02, 0.02))
        return 0  # the next expected pong

    def on_timeout(self, id, state, timer, out):
        # "kick" fires once to start; "resend" re-fires on packet loss.
        if state.get() < self.count:
            out.send(self.target, Ping(state.get()))
            out.set_timer("resend", (0.4, 0.4))

    def on_msg(self, id, state, src, msg, out):
        if isinstance(msg, Pong) and msg.n == state.get():
            self.record.append(msg.n)
            nxt = msg.n + 1
            state.set(nxt)
            if nxt < self.count:
                out.send(src, Ping(nxt))
            else:
                out.cancel_timer("resend")
                self.done.set()


def _free_udp_ports(n):
    socks = [socket.socket(socket.AF_INET, socket.SOCK_DGRAM) for _ in range(n)]
    ports = []
    for s in socks:
        s.bind(("127.0.0.1", 0))
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def test_live_ping_pong_over_loopback_udp():
    count = 5
    ping_port, pong_port = _free_udp_ports(2)
    pinger_id = Id.from_addr("127.0.0.1", ping_port)
    ponger_id = Id.from_addr("127.0.0.1", pong_port)
    serialize, deserialize = json_codec(Ping, Pong)

    record: list = []
    done = threading.Event()
    handles = spawn(
        serialize,
        deserialize,
        [
            (ponger_id, Ponger()),
            (pinger_id, Pinger(ponger_id, count, record, done)),
        ],
        background=True,
    )
    try:
        assert done.wait(timeout=5.0), f"ping-pong stalled; got {record!r}"
        # Deterministic: every pong exactly once, in order (duplicates from
        # a resend race would be dropped by the expected-n check).
        assert record == list(range(count))
    finally:
        for _t, runtime in handles:
            runtime.stopped.set()
        for t, _r in handles:
            t.join(timeout=2.0)
