"""Regression tests for symmetry rewrite plans and the UDP wire codec."""

from stateright_tpu.actor import Id
from stateright_tpu.actor.spawn import json_codec
from stateright_tpu.utils.rewrite_plan import RewritePlan, rewrite


class TestRewritePlan:
    def test_reindex_rewrites_elements(self):
        # Mirrors rewrite_plan.rs:118-123: reindex permutes AND rewrites.
        # "Each actor points at its peer": state[i] holds the peer's Id.
        # Under the swap permutation the canonical form must still point at
        # the peer — not collapse onto "each points at itself".
        plan = RewritePlan([1, 0])  # swap actors 0 and 1
        pointing_at_peer = [Id(1), Id(0)]
        assert plan.reindex(pointing_at_peer) == [Id(1), Id(0)]
        pointing_at_self = [Id(0), Id(1)]
        assert plan.reindex(pointing_at_self) == [Id(0), Id(1)]
        # The two non-equivalent states stay distinguishable.
        assert plan.reindex(pointing_at_peer) != plan.reindex(pointing_at_self)

    def test_reindex_permutes(self):
        plan = RewritePlan([2, 0, 1])
        assert plan.reindex(["c", "a", "b"]) == ["b", "c", "a"]

    def test_rewrite_nested(self):
        plan = RewritePlan([1, 0])
        value = {("x", Id(0)): [Id(1), frozenset({Id(0)})]}
        assert rewrite(value, plan) == {("x", Id(1)): [Id(0), frozenset({Id(1)})]}


class TestJsonCodec:
    def test_nested_named_tuples_round_trip(self):
        from typing import Any, NamedTuple

        class Ping(NamedTuple):
            n: int

        class Req(NamedTuple):
            inner: Any

        ser, de = json_codec(Ping, Req)
        msg = Req(Ping(0))
        assert de(ser(msg)) == msg
        assert isinstance(de(ser(msg)).inner, Ping)

    def test_tuple_set_dict_payloads_round_trip(self):
        ser, de = json_codec()
        for msg in [
            ("ack", 1),
            {"k": (1, 2), 3: "v"},
            frozenset({1, 2}),
            {1, 2},
            [1, ("a", None)],
            "plain",
            7,
            None,
        ]:
            got = de(ser(msg))
            assert got == msg and type(got) is type(msg)


def test_wire_codec_id_in_protocol_payloads():
    """Ids ride inside protocol tuples (Paxos ballots, ABD sequencers) and
    must round-trip the wire codec natively (regression: the spawn CLIs
    crashed on their first internal broadcast without this)."""
    from stateright_tpu.actor import Id
    from stateright_tpu.actor import register as reg
    from stateright_tpu.actor.spawn import json_codec
    from stateright_tpu.models.linearizable_register import AckQuery, Query
    from stateright_tpu.models.paxos import Prepare

    ser, de = json_codec(reg.Internal, Prepare, Query, AckQuery)
    some_id = Id.from_addr("127.0.0.1", 3001)
    for msg in [
        reg.Internal(Prepare((1, some_id))),
        reg.Internal(AckQuery(7, (3, some_id), "V")),
    ]:
        back = de(ser(msg))
        assert back == msg
        # The Id must come back as an Id (addr codec still usable), not int.
        inner = back.msg
        seq = inner.ballot if hasattr(inner, "ballot") else inner.seq
        assert isinstance(seq[1], Id)
