"""Test configuration.

Tests always run on CPU with a virtual 8-device mesh so multi-chip sharding
paths are exercised without TPU hardware.

Environment note: the container's sitecustomize imports jax and registers the
axon TPU plugin at interpreter start, and its register() forces
``jax_platforms="axon,cpu"`` at the *config* level — so the ``JAX_PLATFORMS``
env var alone cannot select CPU, and initializing the axon backend can block
for minutes on the tunnel.  ``jax.config.update`` wins over both, and
``XLA_FLAGS`` is read at CPU-client init, so setting it here (before any
backend init) still works.
"""

import os
import sys

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running parity checks (deselect with -m 'not slow')"
    )
