"""The sharded engine across a REAL process boundary.

Two OS processes x 4 virtual CPU devices form one 8-device global mesh via
``jax.distributed`` (collectives ride the gloo/gRPC transport — the DCN
path of SURVEY §2.8). Both run the same sharded 2pc(3) check SPMD-style;
exact-count parity with the host oracle proves the engine's collectives
(`all_to_all` exchange, psum/pmax reductions, allgather-backed witness
reconstruction) survive a process boundary. The reference checker is
shared-memory only (``/root/reference/src/checker/bfs.rs:89-93``); this is
the scale-out axis it does not have.

The in-suite sharded tests (test_sharded.py) cover the same engine on a
single-process 8-device mesh; this file covers ONLY what the process
boundary changes: non-addressable shards, cross-process collectives, and
host materialization (``_host_read`` / ``_counts_total``).
"""

import os
import socket
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "distributed_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


#: Minimal two-process jax.distributed probe: initialize + one collective
#: over the CPU backend — exactly the call shape these tests depend on.
#: Some jax builds (e.g. the 0.9.x in single-core CI containers) refuse
#: multiprocess collectives on CPU with "Multiprocess computations aren't
#: implemented on the CPU backend"; that is an environment limitation, not
#: a regression in the engine under test, so the whole two-process family
#: skips with the probe's verdict as the reason.
_PROBE = """
import os, sys
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=2"
)
import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(
    coordinator_address="127.0.0.1:%s", num_processes=2,
    process_id=int(sys.argv[1]),
)
from jax.experimental import multihost_utils
multihost_utils.broadcast_one_to_all(1)
print("MP_OK")
"""

_mp_probe_cache = {}


def _mp_cpu_unsupported():
    """None when two-process CPU collectives work here, else the skip
    reason (probed once per session)."""
    if "reason" in _mp_probe_cache:
        return _mp_probe_cache["reason"]
    port = _free_port()
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _PROBE % port, str(rank)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
        )
        for rank in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=180)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        _mp_probe_cache["reason"] = (
            "jax.distributed two-process CPU probe timed out in this "
            "environment"
        )
        return _mp_probe_cache["reason"]
    if all(p.returncode == 0 and "MP_OK" in out for p, out in zip(procs, outs)):
        _mp_probe_cache["reason"] = None
    else:
        tail = next(
            (o for p, o in zip(procs, outs) if p.returncode != 0), outs[0]
        ).strip().splitlines()
        _mp_probe_cache["reason"] = (
            "jax multiprocess-on-CPU is broken in this environment "
            f"(probe failed: {tail[-1] if tail else 'no output'})"
        )
    return _mp_probe_cache["reason"]


def _run_two_process(config: str) -> str:
    """Launch the 2-process mesh on ``config``; returns the (identical on
    both ranks) RESULT payload. Environments whose jax build cannot run
    multiprocess collectives on the CPU backend skip (env-detect probe
    above) — the failure mode is the build, not the engine."""
    reason = _mp_cpu_unsupported()
    if reason:
        pytest.skip(reason)
    port = _free_port()
    env = dict(os.environ)
    # The workers pick their own backend/device-count; the conftest's
    # 8-device XLA_FLAGS would fight the workers' 4-per-process split.
    env.pop("XLA_FLAGS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, str(rank), "2", str(port), config],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            cwd=REPO,
            env=env,
        )
        for rank in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=720)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    results = []
    for rank, (p, out) in enumerate(zip(procs, outs)):
        lines = [l for l in out.splitlines() if l.startswith("RESULT")]
        assert p.returncode == 0 and lines, (
            f"worker {rank} rc={p.returncode}; output tail:\n" + out[-2000:]
        )
        results.append(lines[0].split(" ", 2)[2])  # strip "RESULT pid=k"

    # Every process observes the same global result.
    assert results[0] == results[1]
    return results[0]


def _oracle_2pc3_result() -> str:
    """The host oracle's exact count profile for 2pc(3) (BASELINE.md: 288
    unique / 1,146 generated incl. init), with both SOMETIMES witnesses at
    BFS-minimal depth."""
    from stateright_tpu.models.two_phase_commit import TwoPhaseSys

    oracle = TwoPhaseSys(3).checker().spawn_bfs().join()
    expected_paths = ";".join(
        f"{name}:{len(path)}" for name, path in sorted(oracle.discoveries().items())
    )
    return (
        f"states={oracle.state_count()} unique={oracle.unique_state_count()} "
        f"depth={oracle.max_depth()} paths={expected_paths}"
    )


def test_two_process_mesh_exact_parity():
    assert _run_two_process("2pc") == _oracle_2pc3_result()


def test_two_process_mesh_sorted_structure():
    # The accelerator-default sort-merge visited set across the process
    # boundary: same exact profile, different dedup/compaction lowerings.
    assert _run_two_process("2pc-sorted") == _oracle_2pc3_result()


def test_two_process_mesh_delta_structure_with_flushes():
    # The two-tier delta set at a table size that forces delta flushes and
    # main-tier growth mid-run, across the process boundary.
    assert _run_two_process("2pc-delta") == _oracle_2pc3_result()


def test_two_process_mesh_eventually_counterexample():
    # EVENTUALLY semantics (terminal detection + ebits) and witness-path
    # reconstruction across non-addressable parent-map shards must match
    # the single-chip device engine bit-for-bit.
    from stateright_tpu.core import Property
    from stateright_tpu.test_util import DGraph, PackedDGraph

    graph = (
        DGraph.with_property(Property.eventually("odd", lambda _, s: s % 2 == 1))
        .with_path([0, 2, 4])
        .with_path([4, 6])
    )
    single = (
        PackedDGraph(graph)
        .checker()
        .spawn_xla(frontier_capacity=1 << 9, table_capacity=1 << 12)
        .join()
    )
    expected_paths = ";".join(
        f"{name}:{len(path)}" for name, path in sorted(single.discoveries().items())
    )
    assert "odd" in single.discoveries()  # the cycle-free terminal cex
    assert _run_two_process("ev") == (
        f"states={single.state_count()} unique={single.unique_state_count()} "
        f"depth={single.max_depth()} paths={expected_paths}"
    )


def test_two_process_mesh_host_verified_counterexample():
    # The host-verified-property path across a REAL process boundary: each
    # process compacts candidates on its own shards, the confirmation
    # reads buffers allgathered over the DCN transport, and both processes
    # agree on the confirmed counterexample. Parity target is the
    # single-PROCESS 8-device mesh running the identical config.
    from stateright_tpu.models.single_copy_register import (
        PackedSingleCopyRegister,
    )
    from stateright_tpu.parallel import default_mesh

    local = (
        PackedSingleCopyRegister(2, 2, device_exact=False)
        .checker()
        .spawn_xla(
            mesh=default_mesh(8),
            frontier_capacity=1 << 9,
            table_capacity=1 << 12,
        )
        .join()
    )
    assert "linearizable" in local.discoveries()
    expected_paths = ";".join(
        f"{name}:{len(path)}" for name, path in sorted(local.discoveries().items())
    )
    assert _run_two_process("hv") == (
        f"states={local.state_count()} unique={local.unique_state_count()} "
        f"depth={local.max_depth()} paths={expected_paths}"
    )
