"""CheckerService chaos pins (ISSUE 9 acceptance).

The multi-tenant pool must keep faults per-job and degrade instead of
dying:

- **Admission control**: beyond the queue/session caps, ``submit`` raises
  the typed ``AdmissionError`` with a ``retry_after_s`` back-pressure hint
  — never unbounded queueing; over-cap budgets are rejected without one.
- **Kill-resume smoke** (<30s, rides in ``tools/smoke.sh``): a job
  SIGKILLed mid-superstep requeues, resumes from its own auto-checkpoint
  rotation, and converges to the exact pinned counts; its span trace
  exports as a Chrome trace.
- **Isolation pin**: with two CONCURRENT jobs, SIGSTOP-wedging one (the
  wedged-tunnel signature: heartbeat frozen mid-"dispatch") draws a wedge
  verdict that kills and quarantines only that job's process group; the
  sibling's generated/unique/discovery counts are bit-identical to its
  solo run, and the victim resumes from checkpoint to exact counts.
- **Breaker pin**: K consecutive device wedge verdicts trip the breaker;
  new jobs are served by the host on-demand engine with ``degraded: true``
  and exact counts; a healthy device probe closes the breaker; the pool
  gauges record the trip and the recovery.

Supervision is the real library (``supervise.run_worker`` under
``stateright_tpu/service/core.py``); the worker body is the real service
worker (``stateright_tpu/service/worker.py``), CPU-pinned via the
service's ``platform="cpu"`` knob.
"""

import json
import os
import threading
import time

import pytest

from stateright_tpu.service import (
    AdmissionError,
    CheckerService,
    FleetConfig,
    FleetService,
    ServiceConfig,
)

#: Pinned full-coverage (generated, unique) counts (bench.py EXPECTED_*).
PINNED = {
    "2pc:3": (1_146, 288),
    "2pc:4": (8_258, 1_568),
    "scr:3,1": (6_778, 4_243),
}


@pytest.fixture(autouse=True)
def _no_ambient_chaos(monkeypatch):
    """Each test starts (and ends) with no installed chaos plan — the
    fleet failover smoke installs one process-wide."""
    from stateright_tpu import chaos as chaos_mod

    monkeypatch.delenv("STPU_CHAOS", raising=False)
    chaos_mod.install(None)
    yield
    chaos_mod.install(None)


def _config(tmp_path, **kw):
    base = dict(
        run_dir=str(tmp_path / "svc"),
        platform="cpu",
        default_max_seconds=420.0,
        stall_s=8.0,
        startup_grace_s=240.0,
        poll_s=0.2,
        backoff_s=0.1,
        probe_auto=False,
        # The admission flight-check is pinned by its own tests below;
        # the chaos/breaker pins disable it so each test pays for
        # exactly the machinery it pins (a cold lint subprocess costs
        # ~20 s of jax import + traces on this 1-core box).
        admission_lint=False,
    )
    base.update(kw)
    return ServiceConfig(**base)


_SOLO_CACHE = {}


def _solo(spec):
    """Uninterrupted in-process run of the same model at the worker's
    engine settings — the ground truth a service job (and the isolation
    pin's sibling) must reproduce bit-for-bit."""
    if spec not in _SOLO_CACHE:
        from stateright_tpu.service.registry import resolve

        model, caps = resolve(spec)
        c = model.checker().spawn_xla(**caps).join()
        _SOLO_CACHE[spec] = {
            "generated": c.state_count(),
            "unique": c.unique_state_count(),
            "max_depth": c.max_depth(),
            "discoveries": {
                name: [repr(a) for a in path.into_actions()]
                for name, path in sorted(c.discoveries().items())
            },
        }
    return _SOLO_CACHE[spec]


def _assert_exact(result, spec):
    ref = _solo(spec)
    assert (result["generated"], result["unique"]) == PINNED[spec]
    assert result["generated"] == ref["generated"]
    assert result["unique"] == ref["unique"]
    assert result["max_depth"] == ref["max_depth"]
    assert result["discoveries"] == ref["discoveries"]


# --- admission control -----------------------------------------------------


def test_admission_rejection_at_caps(tmp_path):
    svc = CheckerService(_config(tmp_path, max_inflight=1, max_queue=2))
    # Admission accounting without workers: scheduling disarmed, so
    # submitted jobs stay queued.
    svc._ensure_scheduler = lambda: None
    try:
        with pytest.raises(ValueError, match="unknown model spec"):
            svc.submit("nosuchmodel:9")
        # An over-cap budget is rejected typed, with NO retry hint —
        # retrying the same request cannot help.
        with pytest.raises(AdmissionError) as exc:
            svc.submit("2pc:3", max_seconds=10_000_000.0)
        assert exc.value.retry_after_s is None
        svc.submit("2pc:3")
        svc.submit("2pc:3")
        # Queue full: typed rejection carrying Retry-After, not unbounded
        # queueing.
        with pytest.raises(AdmissionError) as exc:
            svc.submit("2pc:3")
        assert exc.value.retry_after_s is not None
        assert exc.value.retry_after_s > 0
        assert "queue full" in exc.value.reason
        g = svc.gauges()
        assert g["queued"] == 2
        assert g["rejected"] == 2
        assert g["admitted"] == 2
    finally:
        svc.close()


# --- QoS: priority classes, fair share, overload shedding (ISSUE 18) --------


def test_smoke_qos_shed(tmp_path):
    """The tier-0 QoS drill (<30s, tools/smoke.sh): overload sheds the
    lowest class FIRST — typed, class-naming, hint-carrying — while
    higher classes keep admitting up to their own thresholds, and the
    ``qos`` gauge rollup tracks the class/tenant occupancy."""
    svc = CheckerService(_config(tmp_path, max_inflight=1, max_queue=8))
    svc._ensure_scheduler = lambda: None  # admission accounting only
    try:
        with pytest.raises(ValueError, match="priority"):
            svc.submit("2pc:3", priority="platinum")
        with pytest.raises(ValueError, match="deadline_s"):
            svc.submit("2pc:3", deadline_s=-5)
        for _ in range(4):
            svc.submit("2pc:3", tenant="t-batch")  # occupancy 4 = 50 %
        # best_effort sheds at half-full; batch and interactive do not.
        with pytest.raises(AdmissionError) as exc:
            svc.submit("2pc:3", priority="best_effort")
        assert "overloaded: shedding best_effort" in exc.value.reason
        assert exc.value.retry_after_s is not None
        svc.submit("2pc:3")
        svc.submit("2pc:3")  # occupancy 6 = 75 %
        with pytest.raises(AdmissionError, match="shedding batch"):
            svc.submit("2pc:3")
        vip = svc.submit("2pc:3", priority="interactive", deadline_s=60)
        svc.submit("2pc:3", priority="interactive")  # occupancy 8 = cap
        # At the hard cap even interactive rejects — as queue-full, not
        # a shed (there is no lower class left to degrade to).
        with pytest.raises(AdmissionError, match="queue full"):
            svc.submit("2pc:3", priority="interactive")
        g = svc.gauges()
        assert g["sheds"] == 2
        qos = g["qos"]
        assert qos["classes"]["batch"]["queued"] == 6
        assert qos["classes"]["interactive"]["queued"] == 2
        assert qos["classes"]["best_effort"]["queued"] == 0
        assert qos["classes"]["interactive"]["weight"] == 4.0
        assert qos["tenants"]["t-batch"]["queued"] == 4
        assert qos["aging_s"] == svc._cfg.qos_aging_s
        snap = vip.snapshot()
        assert snap["priority"] == "interactive"
        assert snap["tenant"] == "default"
        assert snap["deadline_s"] == 60
    finally:
        svc.close()


def test_starvation_freedom(tmp_path):
    """The no-starvation guarantee: under a sustained higher-class
    backlog, stride fair share already serves best_effort at w/Σw —
    and any job older than ``qos_aging_s * (w_max + 1 - w_class)``
    jumps the rotation entirely (``aged_picks``), so no admitted job
    waits beyond the documented bound."""
    svc = CheckerService(_config(
        tmp_path, max_inflight=0, max_queue=64,
        shed_thresholds={
            "interactive": 1.0, "batch": 1.0, "best_effort": 1.0,
        },
    ))
    svc._ensure_scheduler = lambda: None
    try:
        straggler = svc.submit("2pc:3", priority="best_effort")
        hi = [
            svc.submit("2pc:3", priority="interactive") for _ in range(8)
        ]
        # Fresh jobs: the deterministic stride order gives interactive
        # exactly its 4:1 weighted share of the first 5 slots.
        with svc._lock:
            order = [
                j.priority for j in svc._qos_pick([straggler] + hi, 5)
            ]
        assert order.count("interactive") == 4
        assert order.count("best_effort") == 1
        assert svc.gauges()["aged_picks"] == 0

        # A best_effort job past the aged bound preempts EVERY fresh
        # higher-class sibling — the starvation backstop.
        aged_job = svc.submit("2pc:3", priority="best_effort")
        bound = svc._cfg.qos_aging_s * (svc._w_max + 1.0 - 1.0)
        with svc._lock:
            assert not svc._aged(aged_job, time.time())
            aged_job.created_unix_ts -= bound + 1.0
            picks = svc._qos_pick([aged_job] + hi, 1)
        assert picks == [aged_job]
        assert svc.gauges()["aged_picks"] == 1
    finally:
        svc.close()


def test_qos_edf_and_tenant_inflight_quota(tmp_path):
    """Within a class the pick is earliest-deadline-first (deadline-less
    jobs last); a tenant at its in-flight quota is skipped — not
    starved — and the slot goes to another tenant's job."""
    svc = CheckerService(_config(
        tmp_path, max_inflight=0, max_queue=64,
        tenant_quotas={"capped": {"max_inflight": 1}},
    ))
    svc._ensure_scheduler = lambda: None
    try:
        loose = svc.submit("2pc:3", priority="interactive")
        tight = svc.submit(
            "2pc:3", priority="interactive", deadline_s=30.0
        )
        with svc._lock:
            picks = svc._qos_pick([loose, tight], 1)
        assert picks == [tight]  # later submit, earlier deadline

        a = svc.submit("2pc:3", tenant="capped")
        b = svc.submit("2pc:3", tenant="capped")
        other = svc.submit("2pc:3", tenant="free")
        with svc._cond:
            a.status = "running"  # capped is at max_inflight=1
            picks = svc._qos_pick([b, other], 2)
        # b skipped (quota), other picked; b stays eligible next round.
        assert picks == [other]
        with svc._cond:
            a.status = "done"
            picks = svc._qos_pick([b], 1)
        assert picks == [b]
    finally:
        svc.close()


def test_tenant_quotas_reject_typed(tmp_path):
    """Per-tenant admission quotas: queued quota rejects with a drain
    hint (the tenant's own jobs clearing makes room), a device-seconds
    budget quota rejects with none (retrying cannot help) — both
    counted as ``quota_rejects``."""
    svc = CheckerService(_config(
        tmp_path, max_inflight=0, max_queue=64,
        tenant_max_queued=2,
        tenant_quotas={"broke": {"budget_s": 50.0}},
    ))
    svc._ensure_scheduler = lambda: None
    try:
        svc.submit("2pc:3", tenant="t1")
        svc.submit("2pc:3", tenant="t1")
        with pytest.raises(AdmissionError) as exc:
            svc.submit("2pc:3", tenant="t1")
        assert "queued quota reached" in exc.value.reason
        assert exc.value.retry_after_s is not None
        # Another tenant is untouched by t1's quota.
        svc.submit("2pc:3", tenant="t2")
        with pytest.raises(AdmissionError) as exc:
            svc.submit("2pc:3", tenant="broke", max_seconds=60.0)
        assert "budget exceeded" in exc.value.reason
        assert exc.value.retry_after_s is None
        assert svc.gauges()["quota_rejects"] == 2
    finally:
        svc.close()


def test_retry_after_uses_measured_drain_rate(tmp_path):
    """The Retry-After hint is measured, not guessed: with two or more
    completions in the drain window the hint is jobs-ahead over the
    observed completion rate (per-class when the class has its own
    settlements, pool-wide otherwise); below two it falls back to the
    conservative slot estimate."""
    import time as _time

    svc = CheckerService(_config(tmp_path, max_inflight=1, max_queue=64))
    svc._ensure_scheduler = lambda: None
    try:
        for _ in range(3):
            svc.submit("2pc:3")  # 3 batch jobs ahead
        now = _time.time()
        with svc._lock:
            cold = svc._retry_after(svc._counts(), "batch")
            # Cold pool: the static fallback (3 ahead / 1 slot * half
            # the default budget), not a measured rate.
            assert cold == 3 * svc._cfg.default_max_seconds * 0.5
            svc._drain.append((now - 8.0, "batch"))
            svc._drain.append((now - 4.0, "batch"))
            warm = svc._retry_after(svc._counts(), "batch")
        # Measured: (3 ahead + 1) / (2 completions / ~8 s) ≈ 16 s.
        assert 14.0 <= warm <= 18.0
        with svc._lock:
            # best_effort has no settlements of its own: the pool-wide
            # rate serves, with ALL 3 batch jobs counted ahead of it.
            be = svc._retry_after(svc._counts(), "best_effort")
        assert 14.0 <= be <= 18.0
    finally:
        svc.close()


def test_mux_partition_respects_class(tmp_path):
    """Mux groups form WITHIN a priority class ((spec, priority) key):
    a best_effort lane never rides — and budget-clips — an interactive
    batch."""
    svc = CheckerService(_config(
        tmp_path, max_inflight=0, max_queue=64, mux_k=4,
    ))
    svc._ensure_scheduler = lambda: None
    try:
        jobs = [
            svc.submit("2pc:3", priority="interactive") for _ in range(3)
        ] + [
            svc.submit("2pc:3", priority="best_effort") for _ in range(3)
        ]
        with svc._lock:
            groups = svc._mux_partition(list(jobs))
        assert sorted(len(g) for g in groups) == [3, 3]
        for group in groups:
            assert len({j.priority for j in group}) == 1
    finally:
        svc.close()


# --- admission flight-check (stpu-lint --admission at submit) ---------------

_EVIL_FAMILY = '''
from stateright_tpu.models.two_phase_commit import PackedTwoPhaseSys


class EvilTwoPhase(PackedTwoPhaseSys):
    """The round-3/5 paxos-drift shape, resubmitted as a user model: a
    traced-index .at[] write in the transition kernel (STPU001)."""

    def packed_step(self, words):
        import jax.numpy as jnp

        nxt, valid = super().packed_step(words)
        i = words[0] & jnp.uint32(1)
        nxt = nxt.at[0, i].set(nxt[0, 0])
        return nxt, valid


def evil(args):
    rm = args[0] if args else 3
    return EvilTwoPhase(rm), dict(
        frontier_capacity=1 << 10, table_capacity=1 << 13
    )
'''


def test_admission_lint_rejects_unwaived_finding(tmp_path, monkeypatch):
    """The gate user-submitted specs (STPU_FAMILIES) pass through: a
    model whose kernel carries a pinned-fatal shape is rejected at
    submit with a typed AdmissionError naming the rule — before the
    pool ever schedules it on the device — while a shipped spec admits
    with its verdict recorded in the job snapshot (and so /.pool)."""
    (tmp_path / "evil_family_mod.py").write_text(_EVIL_FAMILY)
    # In-process (registry.parse at submit) and subprocess (the lint and
    # any worker) both resolve the family: sys.path for the former,
    # PYTHONPATH for the latter.
    monkeypatch.syspath_prepend(str(tmp_path))
    monkeypatch.setenv("PYTHONPATH", str(tmp_path))
    monkeypatch.setenv("STPU_FAMILIES", "evil=evil_family_mod:evil")
    svc = CheckerService(_config(tmp_path, admission_lint=True))
    svc._ensure_scheduler = lambda: None  # admission accounting only
    try:
        with pytest.raises(AdmissionError) as exc:
            svc.submit("evil:3")
        assert "STPU001" in str(exc.value)
        assert exc.value.retry_after_s is None  # retrying cannot help
        assert "flight-check" in exc.value.reason

        # User-family verdicts are NEVER memoized (their source lives
        # outside the tree hash): a user who FIXES the model and
        # resubmits to the same pool gets a fresh verdict and admits.
        (tmp_path / "evil_family_mod.py").write_text(
            _EVIL_FAMILY.replace(
                "nxt = nxt.at[0, i].set(nxt[0, 0])\n        ", ""
            )
        )
        fixed = svc.submit("evil:3")
        assert fixed.lint["ok"] is True and fixed.lint["cached"] is False

        # A user family whose module cannot even LOAD is a spec defect,
        # not a tooling failure: rejected (never fail-open admitted).
        monkeypatch.setenv(
            "STPU_FAMILIES",
            "evil=evil_family_mod:evil,ghost=no_such_module_xyz:f",
        )
        with pytest.raises(AdmissionError, match="flight-check"):
            svc.submit("ghost:1")

        job = svc.submit("2pc:3")  # a shipped spec admits
        assert job.lint is not None and job.lint["ok"] is True
        assert job.snapshot()["lint"]["ok"] is True
        # The per-service memo: resubmission pays no second subprocess.
        assert svc.submit("2pc:3").lint["cached"] is True

        g = svc.gauges()
        # evil (rejected) + evil (fixed, unmemoized rerun) + ghost
        # (rejected) + 2pc:3; the second 2pc:3 submit hit the memo.
        assert g["lint_checks"] == 4
        assert g["lint_rejects"] == 2
        assert g["lint_errors"] == 0
        assert g["rejected"] == 2 and g["admitted"] == 3
    finally:
        svc.close()


def test_admission_lint_fails_open_on_tooling_error(tmp_path, monkeypatch):
    """A broken lint TOOL (not a finding) must not take the pool down:
    the job admits with ok=None recorded and lint_errors counted — an
    operator sees a blind gate, tenants keep their fault isolation."""
    from stateright_tpu.service import core as svc_core

    monkeypatch.setattr(svc_core, "_LINT", "/nonexistent/stpu_lint.py")
    svc = CheckerService(_config(tmp_path, admission_lint=True))
    svc._ensure_scheduler = lambda: None
    try:
        job = svc.submit("2pc:3")
        assert job.lint["ok"] is None
        assert job.lint["errors"]
        assert svc.gauges()["lint_errors"] == 1
    finally:
        svc.close()


# --- kill-resume smoke (tools/smoke.sh; <30s) ------------------------------


def test_smoke_service_kill_resume(tmp_path):
    """The tier-0 service crash drill: one SIGKILL mid-superstep, one
    supervised requeue resuming from the job's own checkpoint rotation,
    exact pinned counts, downloadable Chrome trace."""
    svc = CheckerService(_config(tmp_path))
    try:
        job = svc.submit(
            "2pc:3",
            chaos={"die_at_depth": 3, "marker": str(tmp_path / "m1")},
        )
        assert job.wait(timeout=240), job.snapshot()
        assert job.status == "done", job.error
        # First attempt died by SIGKILL (a crash, not a wedge — no breaker
        # evidence); the requeued attempt resumed from the checkpoint.
        assert job.attempts[0]["rc"] == -9
        assert not job.attempts[0]["wedged"]
        assert job.requeues == 1
        assert job.resumed_from is not None
        assert job.result["resumed_from"] == job.resumed_from
        _assert_exact(job.result, "2pc:3")
        assert job.result["metrics"]["checkpoints_written"] >= 1
        # Per-job span trace downloads as Perfetto-loadable Chrome JSON.
        chrome = svc.job_trace_chrome(job.id)
        assert chrome is not None
        with open(chrome) as fh:
            events = json.load(fh)["traceEvents"]
        assert any(e["name"] == "dispatch" for e in events)
        g = svc.gauges()
        assert g["jobs_done"] == 1 and g["crashes"] == 1
        assert g["breaker"]["state"] == "closed"
    finally:
        svc.close()


# --- isolation pin: two concurrent jobs, one SIGSTOP-wedged ----------------


def test_sigstop_isolation_sibling_exact(tmp_path):
    """SIGSTOP freezes the victim's heartbeat mid-"dispatch" (the wedged
    tunnel signature). The service must kill+quarantine ONLY the victim's
    process group and resume it from checkpoint, while the concurrently
    running sibling converges bit-identically to its solo run."""
    svc = CheckerService(_config(tmp_path, max_inflight=2))
    try:
        victim = svc.submit(
            "2pc:4",
            chaos={"freeze_at_depth": 4, "marker": str(tmp_path / "m2")},
        )
        sibling = svc.submit("scr:3,1")
        assert svc.wait_all(timeout=800), svc.metrics()

        # Sibling: untouched by the sibling-job wedge — counts, depth, and
        # discovery paths bit-identical to a solo run.
        assert sibling.status == "done", sibling.error
        assert sibling.wedges == 0 and sibling.requeues == 0
        assert len(sibling.attempts) == 1
        _assert_exact(sibling.result, "scr:3,1")

        # Victim: wedge verdict -> quarantine -> checkpoint resume ->
        # exact counts.
        assert victim.status == "done", victim.error
        assert victim.wedges == 1
        assert victim.attempts[0]["wedged"]
        assert "stale" in victim.attempts[0]["killed"]
        assert victim.resumed_from is not None
        assert victim.result["start_depth"] >= 4  # resumed AT the wedge
        _assert_exact(victim.result, "2pc:4")

        g = svc.gauges()
        assert g["wedge_verdicts"] == 1 and g["requeues"] >= 1
        # One wedge < K: no trip, the pool never degraded.
        assert g["breaker"]["state"] == "closed"
        assert g["breaker_trips"] == 0
    finally:
        svc.close()


# --- breaker: trip -> host fallback -> probe recovery ----------------------


def test_breaker_trip_host_fallback_and_recovery(tmp_path):
    import sys

    svc = CheckerService(
        _config(
            tmp_path,
            stall_s=6.0,
            requeue_limit=1,
            breaker_k=2,
            probe_argv=[sys.executable, "-c", "pass"],
        )
    )
    try:
        # No chaos marker: the sabotage trips on EVERY attempt — the
        # repeatedly-wedging-device shape. 2 attempts = 2 consecutive
        # wedge verdicts = K.
        wedger = svc.submit("2pc:3", chaos={"freeze_at_depth": 2})
        assert wedger.wait(timeout=400), wedger.snapshot()
        assert wedger.status == "failed"
        assert wedger.wedges == 2
        g = svc.gauges()
        assert g["breaker"]["state"] == "open"
        assert g["breaker"]["opened_unix_ts"] is not None
        assert g["breaker_trips"] == 1
        assert g["wedge_verdicts"] == 2
        assert svc.degraded

        # New jobs are served on the host on-demand engine: degraded,
        # exact counts — the pool degrades instead of dying.
        fallback = svc.submit("2pc:3")
        assert fallback.wait(timeout=300), fallback.snapshot()
        assert fallback.status == "done", fallback.error
        assert fallback.engine == "host"
        assert fallback.degraded
        assert fallback.snapshot()["degraded"] is True
        assert fallback.result["degraded"] is True
        assert (
            fallback.result["generated"], fallback.result["unique"]
        ) == PINNED["2pc:3"]
        # Host jobs have no tunnel, hence no heartbeat supervision and no
        # device span trace to download.
        assert svc.job_trace_chrome(fallback.id) is None

        # A healthy device probe closes the breaker; the recovery is in
        # the gauges.
        assert svc.probe_device_now()
        g = svc.gauges()
        assert g["breaker"]["state"] == "closed"
        assert g["breaker"]["opened_unix_ts"] is None
        assert g["breaker_closes"] == 1
        assert g["degraded_jobs"] == 1
        assert not svc.degraded
    finally:
        svc.close()


# --- fleet: multi-device pools, failover migration (ISSUE 15) --------------


def _fleet(tmp_path, devices=2, pool_kw=None, **kw):
    pool = _config(tmp_path)  # run_dir is overwritten per device
    if pool_kw:
        for k, v in pool_kw.items():
            setattr(pool, k, v)
    base = dict(
        run_dir=str(tmp_path / "fleet"),
        devices=devices,
        monitor_interval_s=0.3,
        pool=pool,
    )
    base.update(kw)
    return FleetService(FleetConfig(**base))


def test_smoke_fleet_failover(tmp_path):
    """The <30s fleet tier-0 drill (tools/smoke.sh; ISSUE 15 acceptance):
    a 2-device fleet, `device.lost@n=1` kills the first routed job's
    device mid-job — the victim migrates to the surviving device and
    completes with counts bit-identical to an undisturbed run, while the
    sibling job (on the survivor) never notices."""
    fleet = _fleet(
        tmp_path, devices=2,
        chaos="seed=1;device.lost@n=1:after_s=2",
    )
    try:
        victim = fleet.submit("2pc:3")
        sibling = fleet.submit("2pc:3")
        first_device = victim.device
        assert {victim.device, sibling.device} == {0, 1}  # least-loaded spread
        assert fleet.wait_all(timeout=240), fleet.metrics()

        assert victim.status == "done", (victim.status, victim.error)
        assert len(victim.migrations) == 1
        assert victim.device != first_device  # finished on the survivor
        _assert_exact(victim.result, "2pc:3")

        assert sibling.status == "done", (sibling.status, sibling.error)
        assert sibling.migrations == []
        _assert_exact(sibling.result, "2pc:3")

        g = fleet.gauges()
        assert g["migrations"] == 1
        assert g["devices_lost"] == 1
        assert g["lost_devices"] == [first_device]
        assert g["jobs_evacuated"] == 1
        # The lost device's pool journaled the evacuation (terminal for
        # that pool — a restart would never requeue the job there).
        assert g["devices"][f"device-{first_device}"]["lost"] is True
        # Both fleet jobs' snapshots carry their device.
        snap = fleet.metrics()["jobs"][victim.id]
        assert snap["device"] == f"device-{victim.device}"
        assert snap["migrations"] == 1
    finally:
        fleet.close()


def test_fleet_host_last_resort_only_when_all_open(tmp_path):
    """ISSUE 15 acceptance pin: host-engine degradation happens ONLY when
    every device breaker is open/lost — one healthy sibling means device
    routing, never the host fallback. Routing-only (disarmed pools)."""
    fleet = _fleet(tmp_path, devices=2, pool_kw={"max_inflight": 0})
    try:
        # Device 0's breaker open: routing must pick the healthy sibling
        # on the DEVICE engine — not degrade.
        with fleet.pools[0]._cond:
            fleet.pools[0]._breaker = "open"
        job = fleet.submit("2pc:3")
        assert job.device == 1
        assert job.pool_job.engine_force is None
        assert not fleet.degraded
        assert fleet.gauges()["host_last_resort"] == 0

        # Every breaker open: now — and only now — the host last resort.
        with fleet.pools[1]._cond:
            fleet.pools[1]._breaker = "open"
        assert fleet.degraded
        last = fleet.submit("2pc:3")
        assert last.pool_job.engine_force == "host"
        assert fleet.gauges()["host_last_resort"] == 1
        assert fleet.gauges()["breaker"]["state"] == "open"

        # A closed breaker restores device routing immediately.
        with fleet.pools[0]._cond:
            fleet.pools[0]._breaker = "closed"
        healthy_again = fleet.submit("2pc:3")
        assert healthy_again.device == 0
        assert healthy_again.pool_job.engine_force is None
    finally:
        fleet.close()


def test_fleet_idempotency_and_admission(tmp_path):
    fleet = _fleet(tmp_path, devices=2,
                   pool_kw={"max_inflight": 0, "max_queue": 1})
    try:
        a = fleet.submit("2pc:3", idempotency_key="k1")
        assert fleet.submit("2pc:3", idempotency_key="k1") is a
        assert fleet.gauges()["idem_dedups"] == 1
        # Capacity = 1 queued per device; past both, the typed rejection
        # carries the minimum Retry-After across devices.
        fleet.submit("2pc:3")
        with pytest.raises(AdmissionError) as exc:
            fleet.submit("2pc:3")
        assert exc.value.retry_after_s is not None
        # Over-cap budgets reject identically on every device: no retry
        # hint, and the fleet does not waste submissions on siblings.
        with pytest.raises(AdmissionError) as exc:
            fleet.submit("2pc:3", max_seconds=10_000_000.0)
        assert exc.value.retry_after_s is None
    finally:
        fleet.close()


def test_fleet_concurrent_same_key_submits_dedupe(tmp_path):
    """The fleet-scoped idempotency reservation: concurrent same-key
    submits dedupe to ONE FleetJob (the key reserves under the lock
    BEFORE routing, so the race cannot place the same work on two
    devices) — and a fleet-wide rejection unwinds the reservation so
    the key can be retried."""
    fleet = _fleet(tmp_path, devices=2, pool_kw={"max_inflight": 0})
    try:
        results = []
        threads = [
            threading.Thread(
                target=lambda: results.append(
                    fleet.submit("2pc:3", idempotency_key="kc")
                )
            )
            for _ in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(results) == 4
        assert len({id(r) for r in results}) == 1
        assert sum(
            1 for j in fleet.jobs() if j.idempotency_key == "kc"
        ) == 1
        assert fleet.gauges()["idem_dedups"] == 3
        # Rejection unwind: an over-budget submit fails on every device,
        # the reservation is removed, and the key stays retryable.
        with pytest.raises(AdmissionError):
            fleet.submit("2pc:3", idempotency_key="kr",
                         max_seconds=10_000_000.0)
        assert all(j.idempotency_key != "kr" for j in fleet.jobs())
        retry = fleet.submit("2pc:3", idempotency_key="kr")
        assert retry.pool_job is not None
        # Concurrent submits started exactly ONE monitor thread.
        assert sum(
            1 for t in threading.enumerate()
            if t.name == "stpu-fleet-monitor" and t.is_alive()
        ) == 1
    finally:
        fleet.close()


def test_fleet_submit_unwinds_on_non_admission_errors(tmp_path):
    """A non-admission failure mid-routing (malformed spec → ValueError
    from registry.parse) must not leak the reserved handle as a
    permanently-queued zombie FleetJob: the reservation unwinds, the
    caller sees the original error, and the key stays retryable."""
    fleet = _fleet(tmp_path, devices=2, pool_kw={"max_inflight": 0})
    try:
        with pytest.raises(ValueError):
            fleet.submit("not-a-spec", idempotency_key="kz")
        assert fleet.jobs() == []
        assert fleet.gauges()["rejected"] == 1
        good = fleet.submit("2pc:3", idempotency_key="kz")
        assert good.pool_job is not None
    finally:
        fleet.close()


def _live_monitors():
    return [
        t for t in threading.enumerate()
        if t.name == "stpu-fleet-monitor" and t.is_alive()
    ]


def test_fleet_monitor_idle_exits_and_restarts(tmp_path):
    """The monitor thread exits once every fleet job is terminal (no
    forever-sweep of every pool's locks on a long-lived fleet) and comes
    back on the next submit — and the idle check itself must not
    deadlock on the fleet lock (it runs under it; FleetJob.done would
    re-acquire)."""
    fleet = _fleet(tmp_path, devices=2)
    try:
        fleet.submit("2pc:3")
        assert fleet.wait_all(timeout=240), fleet.metrics()
        deadline = time.monotonic() + 10.0
        while _live_monitors() and time.monotonic() < deadline:
            time.sleep(0.1)
        assert not _live_monitors()  # idle-exited
        again = fleet.submit("2pc:3")
        assert _live_monitors()  # submit brought it back
        assert again.wait(timeout=240)
        assert again.status == "done"
    finally:
        fleet.close()


def test_elastic_quiesce_wake_exact(tmp_path):
    """Elastic pools (docs/service.md "QoS & overload"): a quiesced pool
    leaves routing — work lands on the remaining active pool with counts
    bit-identical to an undisturbed run — and wakes back into rotation;
    ``min_active`` refuses to quiesce the last active pool."""
    fleet = _fleet(tmp_path, devices=2, elastic=True,
                   idle_quiesce_s=3600.0, min_active=1)
    try:
        assert fleet.quiesce_pool(1, reason="test")
        assert not fleet.quiesce_pool(0, reason="test")  # min_active
        assert not fleet.quiesce_pool(1, reason="test")  # already parked
        job = fleet.submit("2pc:3")
        assert job.device == 0
        assert fleet.wait_all(timeout=240), fleet.metrics()
        assert job.status == "done", (job.status, job.error)
        assert job.migrations == []
        _assert_exact(job.result, "2pc:3")
        g = fleet.gauges()
        assert g["quiesced_devices"] == [1]
        assert g["pools_quiesced"] == 1
        assert g["devices"]["device-1"]["quiesced"] is True
        assert g["devices"]["device-1"]["lost"] is False
        assert fleet.wake_pool(1, reason="test")
        g = fleet.gauges()
        assert g["quiesced_devices"] == []
        assert g["pools_woken"] == 1
    finally:
        fleet.close()


def test_elastic_wake_on_pressure(tmp_path):
    """A submission every active pool rejects WITH a retry hint (pure
    pressure) wakes a quiesced pool and places there, instead of
    bouncing the tenant or forcing the host engine. Routing-only
    (disarmed pools)."""
    fleet = _fleet(tmp_path, devices=2, elastic=True,
                   pool_kw={"max_inflight": 0, "max_queue": 1})
    try:
        assert fleet.quiesce_pool(1, reason="test")
        a = fleet.submit("2pc:3")
        assert a.device == 0
        # Pool 0 at its shed limit: the hint-carrying rejection wakes
        # the parked sibling mid-submit.
        b = fleet.submit("2pc:3")
        assert b.device == 1
        g = fleet.gauges()
        assert g["pools_woken"] == 1
        assert g["quiesced_devices"] == []
        # A hint-less rejection (over-cap budget — identical on every
        # device) must NOT wake anything: waking cannot help.
        woken_before = fleet.gauges()["pools_woken"]
        with pytest.raises(AdmissionError) as exc:
            fleet.submit("2pc:3", max_seconds=10_000_000.0)
        assert exc.value.retry_after_s is None
        assert fleet.gauges()["pools_woken"] == woken_before
    finally:
        fleet.close()


def test_evacuate_skips_forced_host_jobs(tmp_path):
    """Forced-host work is device-independent: losing the device must
    not kill it (host attempts don't checkpoint — evacuation would
    discard the progress for zero safety gain)."""
    svc = CheckerService(_config(tmp_path, max_inflight=0))
    try:
        host_job = svc.submit("2pc:3", engine="host")
        dev_job = svc.submit("2pc:3")
        out = svc.evacuate(reason="device lost")
        assert [j.id for j in out] == [dev_job.id]
        assert dev_job.status == "migrated"
        assert host_job.status == "queued"  # rides out the outage
    finally:
        svc.close()


def test_fleet_session_cap_holds_under_concurrent_registration(tmp_path):
    """The fleet-wide max_sessions cap is atomic with registration: N
    concurrent register_interactive calls against a cap of 1 admit
    exactly one session — the rest reject typed (the per-pool caps alone
    would have let several through)."""
    import types

    fleet = _fleet(tmp_path, devices=2, max_sessions=1,
                   pool_kw={"max_inflight": 0, "max_sessions": 4})
    try:
        admitted, rejected = [], []

        def grab():
            checker = types.SimpleNamespace(
                model=lambda: object(), attach_job=lambda jid: None
            )
            try:
                admitted.append(
                    fleet.register_interactive(checker, label="swarm")
                )
            except AdmissionError as e:
                rejected.append(e)

        threads = [threading.Thread(target=grab) for _ in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(admitted) == 1
        assert len(rejected) == 5
        assert all(e.retry_after_s is not None for e in rejected)
        assert fleet.gauges()["interactive"] == 1
        fleet.release_interactive(admitted[0])
        assert fleet.gauges()["interactive"] == 0
    finally:
        fleet.close()


def test_job_snapshot_memoizes_artifact_ages(tmp_path, monkeypatch):
    """ISSUE 15 satellite: snapshot()'s heartbeat/checkpoint ages stat
    each artifact once per poll tick (snapshot_age_ttl_s), not once per
    render — and the snapshot surfaces the pool's device."""
    from stateright_tpu.service import core as svc_core

    svc = CheckerService(_config(tmp_path, max_inflight=0, device="dev7"))
    try:
        job = svc.submit("2pc:3")
        with open(os.path.join(job.dir, "hb.json"), "w") as fh:
            fh.write("{}")
        calls = []
        real = svc_core._mtime_age
        monkeypatch.setattr(
            svc_core, "_mtime_age", lambda p: calls.append(p) or real(p)
        )
        first = job.snapshot()
        assert first["device"] == "dev7"
        assert first["heartbeat_age_s"] is not None
        n = len(calls)
        assert n == 2  # hb + checkpoint, once each
        for _ in range(10):  # a 10-poll render burst within the TTL
            job.snapshot()
        assert len(calls) == n  # memo hit: zero extra stats
    finally:
        svc.close()


# --- the Explorer as one service client ------------------------------------


def test_explorer_is_a_service_client(tmp_path):
    from stateright_tpu.checker.explorer import make_app
    from stateright_tpu.models.two_phase_commit import TwoPhaseSys

    svc = CheckerService(_config(tmp_path, max_sessions=1))
    try:
        app, checker = make_app(TwoPhaseSys(3).checker(), service=svc)
        status = app.status()
        # Pre-service keys unchanged for existing consumers...
        for key in (
            "done", "model", "state_count", "unique_state_count",
            "max_depth", "properties", "recent_path", "metrics",
            "last_checkpoint",
        ):
            assert key in status
        # ...plus the per-job pool fields.
        assert status["job"] is not None
        assert status["degraded"] is False
        assert status["pool"]["interactive"] == 1
        assert status["pool"]["breaker"]["state"] == "closed"
        assert status["metrics"]["job_id"] == status["job"]
        code, pool = app.pool()
        assert code == 200
        assert status["job"] in pool["jobs"]
        assert pool["jobs"][status["job"]]["kind"] == "interactive"

        # Interactive admission: the session cap rejects typed, like any
        # other tenant.
        with pytest.raises(AdmissionError, match="sessions full"):
            make_app(TwoPhaseSys(3).checker(), service=svc)
        job = svc.job(status["job"])
        svc.release_interactive(job)
        app2, _ = make_app(TwoPhaseSys(3).checker(), service=svc)
        assert app2.status()["pool"]["interactive"] == 1
    finally:
        svc.close()


def test_explorer_degrades_while_breaker_open(tmp_path):
    """With the breaker open the service does not hand the device to
    anyone: an auto/xla Explorer session is served by the host on-demand
    engine with ``degraded: true`` in /.status."""
    from stateright_tpu.checker.explorer import make_app
    from stateright_tpu.checker.on_demand import OnDemandChecker
    from stateright_tpu.models.two_phase_commit import PackedTwoPhaseSys

    svc = CheckerService(_config(tmp_path))
    try:
        with svc._cond:
            svc._breaker = "open"
        app, checker = make_app(
            PackedTwoPhaseSys(3).checker(),
            service=svc,
            frontier_capacity=1 << 8,
            table_capacity=1 << 10,
        )
        assert isinstance(checker, OnDemandChecker)
        status = app.status()
        assert status["degraded"] is True
        assert status["pool"]["breaker"]["state"] == "open"
        # The degraded session still serves the model: init states expand
        # on the host engine.
        code, inits = app.states("/")
        assert code == 200 and len(inits) == 1
    finally:
        svc.close()
