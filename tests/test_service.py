"""CheckerService chaos pins (ISSUE 9 acceptance).

The multi-tenant pool must keep faults per-job and degrade instead of
dying:

- **Admission control**: beyond the queue/session caps, ``submit`` raises
  the typed ``AdmissionError`` with a ``retry_after_s`` back-pressure hint
  — never unbounded queueing; over-cap budgets are rejected without one.
- **Kill-resume smoke** (<30s, rides in ``tools/smoke.sh``): a job
  SIGKILLed mid-superstep requeues, resumes from its own auto-checkpoint
  rotation, and converges to the exact pinned counts; its span trace
  exports as a Chrome trace.
- **Isolation pin**: with two CONCURRENT jobs, SIGSTOP-wedging one (the
  wedged-tunnel signature: heartbeat frozen mid-"dispatch") draws a wedge
  verdict that kills and quarantines only that job's process group; the
  sibling's generated/unique/discovery counts are bit-identical to its
  solo run, and the victim resumes from checkpoint to exact counts.
- **Breaker pin**: K consecutive device wedge verdicts trip the breaker;
  new jobs are served by the host on-demand engine with ``degraded: true``
  and exact counts; a healthy device probe closes the breaker; the pool
  gauges record the trip and the recovery.

Supervision is the real library (``supervise.run_worker`` under
``stateright_tpu/service/core.py``); the worker body is the real service
worker (``stateright_tpu/service/worker.py``), CPU-pinned via the
service's ``platform="cpu"`` knob.
"""

import json
import os

import pytest

from stateright_tpu.service import (
    AdmissionError,
    CheckerService,
    ServiceConfig,
)

#: Pinned full-coverage (generated, unique) counts (bench.py EXPECTED_*).
PINNED = {
    "2pc:3": (1_146, 288),
    "2pc:4": (8_258, 1_568),
    "scr:3,1": (6_778, 4_243),
}


def _config(tmp_path, **kw):
    base = dict(
        run_dir=str(tmp_path / "svc"),
        platform="cpu",
        default_max_seconds=420.0,
        stall_s=8.0,
        startup_grace_s=240.0,
        poll_s=0.2,
        backoff_s=0.1,
        probe_auto=False,
        # The admission flight-check is pinned by its own tests below;
        # the chaos/breaker pins disable it so each test pays for
        # exactly the machinery it pins (a cold lint subprocess costs
        # ~20 s of jax import + traces on this 1-core box).
        admission_lint=False,
    )
    base.update(kw)
    return ServiceConfig(**base)


_SOLO_CACHE = {}


def _solo(spec):
    """Uninterrupted in-process run of the same model at the worker's
    engine settings — the ground truth a service job (and the isolation
    pin's sibling) must reproduce bit-for-bit."""
    if spec not in _SOLO_CACHE:
        from stateright_tpu.service.registry import resolve

        model, caps = resolve(spec)
        c = model.checker().spawn_xla(**caps).join()
        _SOLO_CACHE[spec] = {
            "generated": c.state_count(),
            "unique": c.unique_state_count(),
            "max_depth": c.max_depth(),
            "discoveries": {
                name: [repr(a) for a in path.into_actions()]
                for name, path in sorted(c.discoveries().items())
            },
        }
    return _SOLO_CACHE[spec]


def _assert_exact(result, spec):
    ref = _solo(spec)
    assert (result["generated"], result["unique"]) == PINNED[spec]
    assert result["generated"] == ref["generated"]
    assert result["unique"] == ref["unique"]
    assert result["max_depth"] == ref["max_depth"]
    assert result["discoveries"] == ref["discoveries"]


# --- admission control -----------------------------------------------------


def test_admission_rejection_at_caps(tmp_path):
    svc = CheckerService(_config(tmp_path, max_inflight=1, max_queue=2))
    # Admission accounting without workers: scheduling disarmed, so
    # submitted jobs stay queued.
    svc._ensure_scheduler = lambda: None
    try:
        with pytest.raises(ValueError, match="unknown model spec"):
            svc.submit("nosuchmodel:9")
        # An over-cap budget is rejected typed, with NO retry hint —
        # retrying the same request cannot help.
        with pytest.raises(AdmissionError) as exc:
            svc.submit("2pc:3", max_seconds=10_000_000.0)
        assert exc.value.retry_after_s is None
        svc.submit("2pc:3")
        svc.submit("2pc:3")
        # Queue full: typed rejection carrying Retry-After, not unbounded
        # queueing.
        with pytest.raises(AdmissionError) as exc:
            svc.submit("2pc:3")
        assert exc.value.retry_after_s is not None
        assert exc.value.retry_after_s > 0
        assert "queue full" in exc.value.reason
        g = svc.gauges()
        assert g["queued"] == 2
        assert g["rejected"] == 2
        assert g["admitted"] == 2
    finally:
        svc.close()


# --- admission flight-check (stpu-lint --admission at submit) ---------------

_EVIL_FAMILY = '''
from stateright_tpu.models.two_phase_commit import PackedTwoPhaseSys


class EvilTwoPhase(PackedTwoPhaseSys):
    """The round-3/5 paxos-drift shape, resubmitted as a user model: a
    traced-index .at[] write in the transition kernel (STPU001)."""

    def packed_step(self, words):
        import jax.numpy as jnp

        nxt, valid = super().packed_step(words)
        i = words[0] & jnp.uint32(1)
        nxt = nxt.at[0, i].set(nxt[0, 0])
        return nxt, valid


def evil(args):
    rm = args[0] if args else 3
    return EvilTwoPhase(rm), dict(
        frontier_capacity=1 << 10, table_capacity=1 << 13
    )
'''


def test_admission_lint_rejects_unwaived_finding(tmp_path, monkeypatch):
    """The gate user-submitted specs (STPU_FAMILIES) pass through: a
    model whose kernel carries a pinned-fatal shape is rejected at
    submit with a typed AdmissionError naming the rule — before the
    pool ever schedules it on the device — while a shipped spec admits
    with its verdict recorded in the job snapshot (and so /.pool)."""
    (tmp_path / "evil_family_mod.py").write_text(_EVIL_FAMILY)
    # In-process (registry.parse at submit) and subprocess (the lint and
    # any worker) both resolve the family: sys.path for the former,
    # PYTHONPATH for the latter.
    monkeypatch.syspath_prepend(str(tmp_path))
    monkeypatch.setenv("PYTHONPATH", str(tmp_path))
    monkeypatch.setenv("STPU_FAMILIES", "evil=evil_family_mod:evil")
    svc = CheckerService(_config(tmp_path, admission_lint=True))
    svc._ensure_scheduler = lambda: None  # admission accounting only
    try:
        with pytest.raises(AdmissionError) as exc:
            svc.submit("evil:3")
        assert "STPU001" in str(exc.value)
        assert exc.value.retry_after_s is None  # retrying cannot help
        assert "flight-check" in exc.value.reason

        # User-family verdicts are NEVER memoized (their source lives
        # outside the tree hash): a user who FIXES the model and
        # resubmits to the same pool gets a fresh verdict and admits.
        (tmp_path / "evil_family_mod.py").write_text(
            _EVIL_FAMILY.replace(
                "nxt = nxt.at[0, i].set(nxt[0, 0])\n        ", ""
            )
        )
        fixed = svc.submit("evil:3")
        assert fixed.lint["ok"] is True and fixed.lint["cached"] is False

        # A user family whose module cannot even LOAD is a spec defect,
        # not a tooling failure: rejected (never fail-open admitted).
        monkeypatch.setenv(
            "STPU_FAMILIES",
            "evil=evil_family_mod:evil,ghost=no_such_module_xyz:f",
        )
        with pytest.raises(AdmissionError, match="flight-check"):
            svc.submit("ghost:1")

        job = svc.submit("2pc:3")  # a shipped spec admits
        assert job.lint is not None and job.lint["ok"] is True
        assert job.snapshot()["lint"]["ok"] is True
        # The per-service memo: resubmission pays no second subprocess.
        assert svc.submit("2pc:3").lint["cached"] is True

        g = svc.gauges()
        # evil (rejected) + evil (fixed, unmemoized rerun) + ghost
        # (rejected) + 2pc:3; the second 2pc:3 submit hit the memo.
        assert g["lint_checks"] == 4
        assert g["lint_rejects"] == 2
        assert g["lint_errors"] == 0
        assert g["rejected"] == 2 and g["admitted"] == 3
    finally:
        svc.close()


def test_admission_lint_fails_open_on_tooling_error(tmp_path, monkeypatch):
    """A broken lint TOOL (not a finding) must not take the pool down:
    the job admits with ok=None recorded and lint_errors counted — an
    operator sees a blind gate, tenants keep their fault isolation."""
    from stateright_tpu.service import core as svc_core

    monkeypatch.setattr(svc_core, "_LINT", "/nonexistent/stpu_lint.py")
    svc = CheckerService(_config(tmp_path, admission_lint=True))
    svc._ensure_scheduler = lambda: None
    try:
        job = svc.submit("2pc:3")
        assert job.lint["ok"] is None
        assert job.lint["errors"]
        assert svc.gauges()["lint_errors"] == 1
    finally:
        svc.close()


# --- kill-resume smoke (tools/smoke.sh; <30s) ------------------------------


def test_smoke_service_kill_resume(tmp_path):
    """The tier-0 service crash drill: one SIGKILL mid-superstep, one
    supervised requeue resuming from the job's own checkpoint rotation,
    exact pinned counts, downloadable Chrome trace."""
    svc = CheckerService(_config(tmp_path))
    try:
        job = svc.submit(
            "2pc:3",
            chaos={"die_at_depth": 3, "marker": str(tmp_path / "m1")},
        )
        assert job.wait(timeout=240), job.snapshot()
        assert job.status == "done", job.error
        # First attempt died by SIGKILL (a crash, not a wedge — no breaker
        # evidence); the requeued attempt resumed from the checkpoint.
        assert job.attempts[0]["rc"] == -9
        assert not job.attempts[0]["wedged"]
        assert job.requeues == 1
        assert job.resumed_from is not None
        assert job.result["resumed_from"] == job.resumed_from
        _assert_exact(job.result, "2pc:3")
        assert job.result["metrics"]["checkpoints_written"] >= 1
        # Per-job span trace downloads as Perfetto-loadable Chrome JSON.
        chrome = svc.job_trace_chrome(job.id)
        assert chrome is not None
        with open(chrome) as fh:
            events = json.load(fh)["traceEvents"]
        assert any(e["name"] == "dispatch" for e in events)
        g = svc.gauges()
        assert g["jobs_done"] == 1 and g["crashes"] == 1
        assert g["breaker"]["state"] == "closed"
    finally:
        svc.close()


# --- isolation pin: two concurrent jobs, one SIGSTOP-wedged ----------------


def test_sigstop_isolation_sibling_exact(tmp_path):
    """SIGSTOP freezes the victim's heartbeat mid-"dispatch" (the wedged
    tunnel signature). The service must kill+quarantine ONLY the victim's
    process group and resume it from checkpoint, while the concurrently
    running sibling converges bit-identically to its solo run."""
    svc = CheckerService(_config(tmp_path, max_inflight=2))
    try:
        victim = svc.submit(
            "2pc:4",
            chaos={"freeze_at_depth": 4, "marker": str(tmp_path / "m2")},
        )
        sibling = svc.submit("scr:3,1")
        assert svc.wait_all(timeout=800), svc.metrics()

        # Sibling: untouched by the sibling-job wedge — counts, depth, and
        # discovery paths bit-identical to a solo run.
        assert sibling.status == "done", sibling.error
        assert sibling.wedges == 0 and sibling.requeues == 0
        assert len(sibling.attempts) == 1
        _assert_exact(sibling.result, "scr:3,1")

        # Victim: wedge verdict -> quarantine -> checkpoint resume ->
        # exact counts.
        assert victim.status == "done", victim.error
        assert victim.wedges == 1
        assert victim.attempts[0]["wedged"]
        assert "stale" in victim.attempts[0]["killed"]
        assert victim.resumed_from is not None
        assert victim.result["start_depth"] >= 4  # resumed AT the wedge
        _assert_exact(victim.result, "2pc:4")

        g = svc.gauges()
        assert g["wedge_verdicts"] == 1 and g["requeues"] >= 1
        # One wedge < K: no trip, the pool never degraded.
        assert g["breaker"]["state"] == "closed"
        assert g["breaker_trips"] == 0
    finally:
        svc.close()


# --- breaker: trip -> host fallback -> probe recovery ----------------------


def test_breaker_trip_host_fallback_and_recovery(tmp_path):
    import sys

    svc = CheckerService(
        _config(
            tmp_path,
            stall_s=6.0,
            requeue_limit=1,
            breaker_k=2,
            probe_argv=[sys.executable, "-c", "pass"],
        )
    )
    try:
        # No chaos marker: the sabotage trips on EVERY attempt — the
        # repeatedly-wedging-device shape. 2 attempts = 2 consecutive
        # wedge verdicts = K.
        wedger = svc.submit("2pc:3", chaos={"freeze_at_depth": 2})
        assert wedger.wait(timeout=400), wedger.snapshot()
        assert wedger.status == "failed"
        assert wedger.wedges == 2
        g = svc.gauges()
        assert g["breaker"]["state"] == "open"
        assert g["breaker"]["opened_unix_ts"] is not None
        assert g["breaker_trips"] == 1
        assert g["wedge_verdicts"] == 2
        assert svc.degraded

        # New jobs are served on the host on-demand engine: degraded,
        # exact counts — the pool degrades instead of dying.
        fallback = svc.submit("2pc:3")
        assert fallback.wait(timeout=300), fallback.snapshot()
        assert fallback.status == "done", fallback.error
        assert fallback.engine == "host"
        assert fallback.degraded
        assert fallback.snapshot()["degraded"] is True
        assert fallback.result["degraded"] is True
        assert (
            fallback.result["generated"], fallback.result["unique"]
        ) == PINNED["2pc:3"]
        # Host jobs have no tunnel, hence no heartbeat supervision and no
        # device span trace to download.
        assert svc.job_trace_chrome(fallback.id) is None

        # A healthy device probe closes the breaker; the recovery is in
        # the gauges.
        assert svc.probe_device_now()
        g = svc.gauges()
        assert g["breaker"]["state"] == "closed"
        assert g["breaker"]["opened_unix_ts"] is None
        assert g["breaker_closes"] == 1
        assert g["degraded_jobs"] == 1
        assert not svc.degraded
    finally:
        svc.close()


# --- the Explorer as one service client ------------------------------------


def test_explorer_is_a_service_client(tmp_path):
    from stateright_tpu.checker.explorer import make_app
    from stateright_tpu.models.two_phase_commit import TwoPhaseSys

    svc = CheckerService(_config(tmp_path, max_sessions=1))
    try:
        app, checker = make_app(TwoPhaseSys(3).checker(), service=svc)
        status = app.status()
        # Pre-service keys unchanged for existing consumers...
        for key in (
            "done", "model", "state_count", "unique_state_count",
            "max_depth", "properties", "recent_path", "metrics",
            "last_checkpoint",
        ):
            assert key in status
        # ...plus the per-job pool fields.
        assert status["job"] is not None
        assert status["degraded"] is False
        assert status["pool"]["interactive"] == 1
        assert status["pool"]["breaker"]["state"] == "closed"
        assert status["metrics"]["job_id"] == status["job"]
        code, pool = app.pool()
        assert code == 200
        assert status["job"] in pool["jobs"]
        assert pool["jobs"][status["job"]]["kind"] == "interactive"

        # Interactive admission: the session cap rejects typed, like any
        # other tenant.
        with pytest.raises(AdmissionError, match="sessions full"):
            make_app(TwoPhaseSys(3).checker(), service=svc)
        job = svc.job(status["job"])
        svc.release_interactive(job)
        app2, _ = make_app(TwoPhaseSys(3).checker(), service=svc)
        assert app2.status()["pool"]["interactive"] == 1
    finally:
        svc.close()


def test_explorer_degrades_while_breaker_open(tmp_path):
    """With the breaker open the service does not hand the device to
    anyone: an auto/xla Explorer session is served by the host on-demand
    engine with ``degraded: true`` in /.status."""
    from stateright_tpu.checker.explorer import make_app
    from stateright_tpu.checker.on_demand import OnDemandChecker
    from stateright_tpu.models.two_phase_commit import PackedTwoPhaseSys

    svc = CheckerService(_config(tmp_path))
    try:
        with svc._cond:
            svc._breaker = "open"
        app, checker = make_app(
            PackedTwoPhaseSys(3).checker(),
            service=svc,
            frontier_capacity=1 << 8,
            table_capacity=1 << 10,
        )
        assert isinstance(checker, OnDemandChecker)
        status = app.status()
        assert status["degraded"] is True
        assert status["pool"]["breaker"]["state"] == "open"
        # The degraded session still serves the model: init states expand
        # on the host engine.
        code, inits = app.states("/")
        assert code == 200 and len(inits) == 1
    finally:
        svc.close()
