"""Device-side symmetry reduction (stateright_tpu/sym; docs/symmetry.md).

Soundness ladder, weakest to strongest:

- the compiled kernel is bit-identical to its host numpy twin over every
  reachable state (differential fuzz);
- canonicalization is idempotent and CLASS-INVARIANT: every block
  permutation of a state canonicalizes to the same representative — the
  property that makes reduced counts equal the number of reachable
  equivalence classes on ANY traversal;
- the device engines (single-chip, on-demand, 8-device mesh) agree with
  the host object-state oracle (``object_canonicalizer``) on counts and
  discoveries, across all three dedup backends;
- unsupported paths refuse typed (``SymmetryUnsupported``) instead of
  silently exploring full-space or, worse, silently under-counting.

Count provenance (see docs/symmetry.md "Full vs partial canonicalization"):
the reference's 665 at 2pc rm=5 (2pc.rs:170) is a DFS-traversal artifact
of its PARTIAL canon (rm_state sort only) — reproduced here on the host
DFS. The spec-compiled kernel is a FULL canonicalization, so the device
count is the true class count: 80 / 166 / 314 at rm = 3 / 4 / 5.
"""

import itertools

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from stateright_tpu.models.increment import PackedIncrement
from stateright_tpu.models.increment_lock import PackedIncrementLock
from stateright_tpu.models.two_phase_commit import PackedTwoPhaseSys, TwoPhaseSys
from stateright_tpu.sym import (
    BlockGroup,
    SymmetrySpec,
    SymmetryUnsupported,
    canonicalize_host,
    compile_canon,
    object_canonicalizer,
)

CAPS = dict(frontier_capacity=1 << 10, table_capacity=1 << 13)


def _reachable_rows(model) -> np.ndarray:
    """Every reachable packed row of the FULL (unreduced) space."""
    seen = set()
    stack = list(model.init_states())
    while stack:
        s = stack.pop()
        if s in seen:
            continue
        seen.add(s)
        stack.extend(model.next_states(s))
    return np.stack([np.asarray(model.pack(s), np.uint32) for s in seen])


def _permute_blocks(spec: SymmetrySpec, row: np.ndarray, perm) -> np.ndarray:
    """Apply a block permutation through the spec's own lane positions:
    new block b takes old block perm[b]'s lane values. Generates the
    group orbit the kernel claims to collapse — no model cooperation
    needed, so the helper can't share a bug with the kernel under test."""
    out = np.array(row, dtype=np.uint32, copy=True)
    for g in spec.groups:
        for lane in g.lanes:
            mask = (1 << lane.bits) - 1
            vals = [
                (int(row[w]) >> s) & mask for (w, s) in lane.positions
            ]
            for new_b, (w, s) in enumerate(lane.positions):
                out[w] = np.uint32(
                    (int(out[w]) & ~(mask << s)) | (vals[perm[new_b]] << s)
                )
    return out


# --- the <30s smoke drill (tools/smoke.sh) ---------------------------------


def test_smoke_symmetry():
    """Device symmetry end-to-end in one small model: forced-on device run
    collapses 288 -> 80 classes, agrees with the host object-state oracle,
    and reports its spec tag through metrics."""
    m = PackedTwoPhaseSys(3)
    dev = m.checker().spawn_xla(symmetry="on", **CAPS).join()
    assert dev.unique_state_count() == 80
    dev.assert_properties()
    tag = dev.metrics()["symmetry"]
    assert tag == f"spec:{m.symmetry_spec.spec_hash()[:12]}"

    host = (
        TwoPhaseSys(3)
        .checker()
        .symmetry_fn(object_canonicalizer(m))
        .spawn_bfs()
        .join()
    )
    assert host.unique_state_count() == 80
    host.assert_properties()

    # Off stays full-space; the tag is None on every off path.
    off = m.checker().spawn_xla(**CAPS).join()
    assert off.unique_state_count() == 288
    assert off.metrics()["symmetry"] is None


# --- count pins (class counts are traversal-invariant) ---------------------


def test_device_2pc_rm4_class_count():
    c = (
        PackedTwoPhaseSys(4)
        .checker()
        .symmetry()
        .spawn_xla(frontier_capacity=1 << 11, table_capacity=1 << 13)
        .join()
    )
    assert c.unique_state_count() == 166
    c.assert_properties()


class _FullSpace:
    """Replace the always-props with an unreachable sometimes so the search
    exhausts the space — increment's "fin" race would otherwise early-exit
    the engine before the count stabilizes (same trick as
    test_packed_increment.py)."""

    def properties(self):
        from stateright_tpu.core import Property

        return [Property.sometimes("unreachable", lambda _m, _s: False)]

    def packed_properties(self, words):
        return jnp.stack([jnp.bool_(False)])


class _IncrementFull(_FullSpace, PackedIncrement):
    pass


class _IncrementLockFull(_FullSpace, PackedIncrementLock):
    pass


@pytest.mark.parametrize(
    "model_cls,n,full,reduced",
    [
        (_IncrementFull, 2, 13, 8),
        (_IncrementFull, 3, 84, 22),
        (_IncrementLockFull, 2, 17, 9),
        (_IncrementLockFull, 3, 61, 13),
    ],
)
def test_device_increment_class_counts(model_cls, n, full, reduced):
    caps = dict(frontier_capacity=1 << 8, table_capacity=1 << 10)
    off = model_cls(n).checker().spawn_xla(**caps).join()
    assert off.unique_state_count() == full
    on = model_cls(n).checker().symmetry().spawn_xla(**caps).join()
    assert on.unique_state_count() == reduced


def test_increment_race_survives_reduction():
    """The "fin" race counterexample (increment.rs:63-71) must still
    surface from the symmetry-reduced space — a reduction that lost a
    discovery would be unsound, not just miscounted."""
    caps = dict(frontier_capacity=1 << 8, table_capacity=1 << 10)
    on = PackedIncrement(2).checker().symmetry().spawn_xla(**caps).join()
    assert "fin" in on.discoveries()
    final = on.discoveries()["fin"].last_state()
    assert sum(1 for _t, pc in final.s if pc == 3) != final.i


@pytest.mark.parametrize("dedup", ["sorted", "hash", "delta"])
def test_all_dedups_agree(dedup):
    c = (
        PackedTwoPhaseSys(3)
        .checker()
        .spawn_xla(symmetry="on", dedup=dedup, **CAPS)
        .join()
    )
    assert c.unique_state_count() == 80
    c.assert_properties()


def test_host_full_canon_is_traversal_invariant():
    """Class-invariant canon => BFS and DFS visit the same class count
    (at rm=5 that's 314, NOT the reference's 665 — the 665 is the
    partial-canon DFS artifact pinned in test_two_phase_commit.py and
    below). rm=4 keeps this under a second."""
    canon = object_canonicalizer(PackedTwoPhaseSys(4))
    bfs = TwoPhaseSys(4).checker().symmetry_fn(canon).spawn_bfs().join()
    dfs = TwoPhaseSys(4).checker().symmetry_fn(canon).spawn_dfs().join()
    assert bfs.unique_state_count() == dfs.unique_state_count() == 166


@pytest.mark.slow
def test_host_full_canon_rm5_matches_device():
    """The rm=5 host oracle for test_xla_engine.py's device 314 pin; the
    same run shows the reference's partial canon (``.symmetry()`` on the
    object model = rm_state-sort-only ``representative()``) is traversal-
    DEPENDENT: its DFS lands on the reference's 665 (2pc.rs:170) while
    its BFS lands elsewhere — neither is the class count."""
    m = PackedTwoPhaseSys(5)
    full_dfs = (
        TwoPhaseSys(5)
        .checker()
        .symmetry_fn(object_canonicalizer(m))
        .spawn_dfs()
        .join()
    )
    assert full_dfs.unique_state_count() == 314

    partial_dfs = TwoPhaseSys(5).checker().symmetry().spawn_dfs().join()
    assert partial_dfs.unique_state_count() == 665
    partial_bfs = TwoPhaseSys(5).checker().symmetry().spawn_bfs().join()
    assert partial_bfs.unique_state_count() != 665
    assert partial_bfs.unique_state_count() >= 314


def test_device_matches_host_oracle_discoveries():
    m = PackedTwoPhaseSys(3)
    dev = m.checker().symmetry().spawn_xla(**CAPS).join()
    host = (
        TwoPhaseSys(3)
        .checker()
        .symmetry_fn(object_canonicalizer(m))
        .spawn_bfs()
        .join()
    )
    assert dev.unique_state_count() == host.unique_state_count() == 80
    assert set(dev.discoveries()) == set(host.discoveries())


# --- kernel soundness ------------------------------------------------------


def test_kernel_matches_host_twin_and_is_idempotent():
    m = PackedTwoPhaseSys(3)
    rows = _reachable_rows(m)
    dev = np.asarray(jax.jit(jax.vmap(compile_canon(m.symmetry_spec)))(
        jnp.asarray(rows)
    ))
    host = np.stack([canonicalize_host(m.symmetry_spec, r) for r in rows])
    np.testing.assert_array_equal(dev, host)
    # canon o canon == canon (a canonical form is its own representative).
    host2 = np.stack([canonicalize_host(m.symmetry_spec, r) for r in host])
    np.testing.assert_array_equal(host2, host)


@pytest.mark.parametrize(
    "model", [PackedTwoPhaseSys(3), PackedIncrement(3), PackedIncrementLock(3)]
)
def test_canon_is_class_invariant(model):
    """EVERY block permutation of EVERY reachable state canonicalizes to
    the same representative — the full-canonicalization property that
    makes reduced counts traversal-invariant class counts."""
    spec = model.symmetry_spec
    rows = _reachable_rows(model)
    count = spec.groups[0].count
    base = np.stack([canonicalize_host(spec, r) for r in rows])
    for perm in itertools.permutations(range(count)):
        permuted = np.stack([_permute_blocks(spec, r, perm) for r in rows])
        canon = np.stack([canonicalize_host(spec, r) for r in permuted])
        np.testing.assert_array_equal(canon, base)


@pytest.mark.parametrize("model", [PackedIncrement(3), PackedIncrementLock(3)])
def test_spec_kernel_equals_packed_representative(model):
    """increment/increment-lock derive their spec via from_layout over the
    same (t, pc) key their hand-written packed_representative sorts by —
    the spec kernel must be bit-identical to it (the models' docstrings
    promise it; drift means from_layout or the kernel regressed)."""
    rows = jnp.asarray(_reachable_rows(model))
    spec_out = np.asarray(jax.vmap(compile_canon(model.symmetry_spec))(rows))
    hand_out = np.asarray(jax.vmap(model.packed_representative)(rows))
    np.testing.assert_array_equal(spec_out, hand_out)


# --- typed refusal (SymmetryUnsupported regressions) -----------------------


def test_forced_on_without_capability_refuses():
    """Models with neither a spec nor packed_representative refuse typed
    on every engine entry point (the regression: earlier builds silently
    fell back to full-space on some paths)."""
    from stateright_tpu.models.linearizable_register import PackedAbd

    with pytest.raises(SymmetryUnsupported) as ei:
        PackedAbd(2, 2).checker().spawn_xla(symmetry="on", **CAPS)
    assert ei.value.engine == "xla"
    assert "neither" in ei.value.reason
    with pytest.raises(SymmetryUnsupported):
        PackedAbd(2, 2).checker().spawn_on_demand(
            engine="xla", symmetry="on", **CAPS
        )


def test_bad_symmetry_spec_type_refuses():
    class Broken(PackedTwoPhaseSys):
        def __init__(self):
            super().__init__(3)
            self.symmetry_spec = "not-a-spec"

    with pytest.raises(SymmetryUnsupported, match="expected SymmetrySpec"):
        Broken().checker().spawn_xla(symmetry="on", **CAPS)


def test_spec_beyond_state_words_refuses():
    class Widened(PackedTwoPhaseSys):
        def __init__(self):
            super().__init__(3)
            w = self.state_words
            self.symmetry_spec = SymmetrySpec(
                [
                    BlockGroup(
                        "ghost", 2,
                        (SymmetrySpec.lane(
                            "ghost", 2, positions=[(w, 0), (w, 2)]
                        ),),
                    )
                ]
            )

    with pytest.raises(SymmetryUnsupported, match="state_words"):
        Widened().checker().spawn_xla(symmetry="on", **CAPS)


def test_hv_properties_refuse_symmetry():
    """A symmetry-reduced frontier surfaces ONE member per class; the
    host-verified fallback re-checks concrete states, so an asymmetric hv
    property could silently miss its witness — both device engines must
    refuse, not under-check."""

    class HvTwoPhase(PackedTwoPhaseSys):
        def __init__(self, rm):
            super().__init__(rm)
            self.host_verified_properties = frozenset({"commit agreement"})

    with pytest.raises(SymmetryUnsupported, match="host-verified"):
        HvTwoPhase(3).checker().symmetry().spawn_xla(**CAPS)

    if len(jax.devices()) >= 8:
        from stateright_tpu.parallel import default_mesh

        with pytest.raises(SymmetryUnsupported, match="host-verified"):
            HvTwoPhase(3).checker().symmetry().spawn_xla(
                mesh=default_mesh(8), **CAPS
            )


def test_object_canonicalizer_requires_spec():
    from stateright_tpu.models.linearizable_register import PackedAbd

    with pytest.raises(SymmetryUnsupported):
        object_canonicalizer(PackedAbd(2, 2))


# --- spec validation -------------------------------------------------------


def _group(*lanes, count=2, name="g"):
    return SymmetrySpec([BlockGroup(name, count, tuple(lanes))])


def test_spec_validation_errors():
    lane = SymmetrySpec.lane
    # Overlapping bits across lanes of one group.
    with pytest.raises(ValueError, match="overlap"):
        _group(
            lane("a", 2, positions=[(0, 0), (0, 2)]),
            lane("b", 2, positions=[(0, 1), (0, 3)]),
        )
    with pytest.raises(ValueError, match="bits"):
        _group(lane("a", 0, positions=[(0, 0), (0, 1)]))
    with pytest.raises(ValueError, match="bits"):
        _group(lane("a", 33, positions=[(0, 0), (1, 0)]))
    # Every lane must carry one position per block.
    with pytest.raises(ValueError, match="positions"):
        _group(lane("a", 1, positions=[(0, 0), (0, 1), (0, 2)]))
    # A lane spilling past bit 32 of its word.
    with pytest.raises(ValueError, match="fit"):
        _group(lane("a", 4, positions=[(0, 30), (0, 0)]))
    # A one-block "group" has no symmetry to reduce.
    with pytest.raises(ValueError, match="count"):
        _group(lane("a", 1, positions=[(0, 0)]), count=1)
    with pytest.raises(ValueError, match="no lanes"):
        _group(count=2)
    with pytest.raises(ValueError, match="at least one"):
        SymmetrySpec([])


def test_spec_hash_is_layout_sensitive():
    lane = SymmetrySpec.lane
    a = _group(lane("t", 2, positions=[(0, 0), (0, 2)]))
    b = _group(lane("t", 2, positions=[(0, 0), (0, 4)]))
    assert a.spec_hash() != b.spec_hash()
    assert a.spec_hash() == _group(
        lane("t", 2, positions=[(0, 0), (0, 2)])
    ).spec_hash()


# --- mode resolution (spawn arg vs STPU_SYMMETRY) --------------------------


def test_env_forces_on(monkeypatch):
    monkeypatch.setenv("STPU_SYMMETRY", "1")
    c = PackedTwoPhaseSys(3).checker().spawn_xla(**CAPS).join()
    assert c.unique_state_count() == 80


def test_env_off_beats_builder(monkeypatch):
    monkeypatch.setenv("STPU_SYMMETRY", "off")
    c = PackedTwoPhaseSys(3).checker().symmetry().spawn_xla(**CAPS).join()
    assert c.unique_state_count() == 288
    assert c.metrics()["symmetry"] is None


def test_arg_beats_env(monkeypatch):
    monkeypatch.setenv("STPU_SYMMETRY", "1")
    c = PackedTwoPhaseSys(3).checker().spawn_xla(symmetry="off", **CAPS).join()
    assert c.unique_state_count() == 288


def test_invalid_mode_raises():
    with pytest.raises(ValueError, match="auto/on/off"):
        PackedTwoPhaseSys(3).checker().spawn_xla(symmetry="sideways", **CAPS)


# --- checkpoint identity ---------------------------------------------------


def test_checkpoint_symmetry_mismatch_refuses(tmp_path):
    """A checkpoint's visited table holds CANONICAL fingerprints; resuming
    it under a different canonicalization would silently corrupt dedup —
    the meta carries the sym tag and a mismatched resume fails typed."""
    path = str(tmp_path / "ck.npz")
    partial = PackedTwoPhaseSys(3).checker().spawn_xla(symmetry="on", **CAPS)
    partial._run_block()
    partial.save_checkpoint(path)

    with pytest.raises(ValueError, match="symmetry"):
        PackedTwoPhaseSys(3).checker().spawn_xla(checkpoint=path, **CAPS)

    resumed = PackedTwoPhaseSys(3).checker().spawn_xla(
        symmetry="on", checkpoint=path, **CAPS
    ).join()
    assert resumed.unique_state_count() == 80
    resumed.assert_properties()


def test_old_checkpoints_without_sym_key_still_load():
    from stateright_tpu.checkpoint import validate_symmetry

    validate_symmetry({}, None)  # pre-symmetry meta: skip, don't refuse
    validate_symmetry({}, "spec:abc")
    validate_symmetry({"symmetry": None}, None)
    with pytest.raises(ValueError):
        validate_symmetry({"symmetry": "spec:a"}, "spec:b")
    with pytest.raises(ValueError):
        validate_symmetry({"symmetry": "spec:a"}, None)


# --- engines beyond the single-chip batch path -----------------------------


def test_on_demand_targeted_expansion_canonicalizes():
    m = PackedTwoPhaseSys(3)
    c = m.checker().symmetry().spawn_on_demand(engine="xla", **CAPS)
    init = list(m.init_states())[0]
    c.check_state(init)  # targeted: one compiled superstep, canon applied
    assert c.unique_state_count() >= 1
    c.run_to_completion()
    c.join()
    assert c.unique_state_count() == 80
    c.assert_properties()


@pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the 8-device virtual CPU mesh"
)
def test_mesh_symmetry_matches_single_chip():
    from stateright_tpu.parallel import default_mesh

    m = PackedTwoPhaseSys(3)
    c = m.checker().symmetry().spawn_xla(mesh=default_mesh(8), **CAPS).join()
    assert c.unique_state_count() == 80
    c.assert_properties()
    assert c.metrics()["symmetry"] == f"spec:{m.symmetry_spec.spec_hash()[:12]}"


def test_level_log_carries_sym_tag():
    c = PackedTwoPhaseSys(3).checker().symmetry().spawn_xla(**CAPS).join()
    tag = c.metrics()["symmetry"]
    assert tag and tag.startswith("spec:")
    assert c.level_log
    assert all(row["sym"] == tag for row in c.level_log)

    off = PackedTwoPhaseSys(3).checker().spawn_xla(**CAPS).join()
    assert all(row["sym"] is None for row in off.level_log)


# --- service integration ---------------------------------------------------


def test_sym_families_matches_model_capability():
    """registry.SYM_FAMILIES is static (the jax-free parent can't import
    models); drift against the models' actual capability is THIS failure."""
    from stateright_tpu.service import registry

    for family in registry.FAMILIES:
        model, _ = registry.resolve(family)
        ships = getattr(model, "symmetry_spec", None) is not None
        assert ships == (family in registry.SYM_FAMILIES), (
            f"{family}: symmetry_spec={ships} but SYM_FAMILIES says "
            f"{family in registry.SYM_FAMILIES}"
        )


def test_mux_partition_keys_on_symmetry():
    """Mux lanes share ONE compiled canonicalization (xla_mux._check_lanes
    pins _sym_tag across the group), so the scheduler must never batch a
    symmetry-on job with a symmetry-off sibling."""
    from types import SimpleNamespace

    from stateright_tpu.service.core import CheckerService

    def job(symmetry):
        return SimpleNamespace(
            spec="2pc:3", priority="batch", symmetry=symmetry,
            engine_force=None, seed_checkpoint=None, _mux_solo=False,
        )

    fake = SimpleNamespace(
        _cfg=SimpleNamespace(mux_k=4), _breaker="closed"
    )
    a, b, c = job(None), job(None), job("on")
    groups = CheckerService._mux_partition(fake, [a, b, c])
    assert sorted(len(g) for g in groups) == [1, 2]
    assert [c] in groups  # the symmetry-on job rides alone
