"""Ordered-reliable-link tests, porting ordered_reliable_link.rs:207-316:
under a lossy duplicating network (bounded to <4 in-flight messages) the ORL
must prevent redelivery, preserve order, and be able to deliver."""

from typing import NamedTuple

from stateright_tpu import Expectation
from stateright_tpu.actor import ActorModel, DeliverAction, Id, Network
from stateright_tpu.actor.ordered_reliable_link import (
    ActorWrapper,
    Deliver,
)


class OrlMsg(NamedTuple):
    value: int


class Sender:
    def __init__(self, receiver_id):
        self.receiver_id = receiver_id

    def on_start(self, id, out):
        out.send(self.receiver_id, OrlMsg(42))
        out.send(self.receiver_id, OrlMsg(43))
        return ()

    def on_msg(self, id, state, src, msg, out):
        pass

    def on_timeout(self, id, state, timer, out):
        pass


class Receiver:
    def on_start(self, id, out):
        return ()

    def on_msg(self, id, state, src, msg, out):
        state.set(state.get() + ((src, msg),))

    def on_timeout(self, id, state, timer, out):
        pass


def _received(state):
    return state.actor_states[1].wrapped_state


def model():
    return (
        ActorModel(cfg=None, init_history=())
        .actor(ActorWrapper.with_default_timeout(Sender(Id(1))))
        .actor(ActorWrapper.with_default_timeout(Receiver()))
        .init_network(Network.new_unordered_duplicating())
        .lossy_network(True)
        .property(
            Expectation.ALWAYS,
            "no redelivery",
            lambda _, state: (
                sum(1 for _, m in _received(state) if m.value == 42) < 2
                and sum(1 for _, m in _received(state) if m.value == 43) < 2
            ),
        )
        .property(
            Expectation.ALWAYS,
            "ordered",
            lambda _, state: all(
                a.value <= b.value
                for (_, a), (_, b) in zip(_received(state), _received(state)[1:])
            ),
        )
        .property(
            Expectation.SOMETIMES,
            "delivered",
            lambda _, state: _received(state)
            == ((Id(0), OrlMsg(42)), (Id(0), OrlMsg(43))),
        )
        .within_boundary_fn(lambda _, state: len(state.network) < 4)
    )


def test_messages_are_not_delivered_twice():
    model().checker().spawn_bfs().join().assert_no_discovery("no redelivery")


def test_messages_are_delivered_in_order():
    model().checker().spawn_bfs().join().assert_no_discovery("ordered")


def test_messages_are_eventually_delivered():
    checker = model().checker().spawn_bfs().join()
    checker.assert_discovery(
        "delivered",
        [
            DeliverAction(Id(0), Id(1), Deliver(1, OrlMsg(42))),
            DeliverAction(Id(0), Id(1), Deliver(2, OrlMsg(43))),
        ],
    )
